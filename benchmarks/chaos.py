"""Chaos lane: FaultPlan drills over a tiny epoch — the resilience layer's
evidence job (mega_session ``chaos`` stage, log-only).

Deterministic drills, each asserting the property the resilience
layer guarantees (quiver_tpu/resilience/):

* **guard**: a NaN-poisoned batch inside the fused step leaves params
  bit-unchanged and the skip counter reads exactly 1;
* **retry**: seeded transient sampler faults are absorbed by the
  Prefetcher's bounded backoff and the delivered stream is bit-identical
  to a fault-free run;
* **preempt/resume**: a simulated kill mid-epoch, then resume() — the
  remaining loss trajectory is bit-identical to the uninterrupted run;
* **resize**: the elastic drill — kill an F-shard run mid-epoch, resume
  onto HALF the devices (``resume(mesh=)``: topology + three-tier feature
  store re-planned, blocks-per-device doubled) and the remaining loss
  trajectory + final params stay bit-identical to the uninterrupted
  full-mesh run;
* **corrupt**: flip manifest-covered bytes in the NEWEST checkpoint (and
  plant an uncommitted partial directory) — resume() quarantines both and
  falls back to the previous valid checkpoint, no manual intervention;
* **cold-outage**: a cold-tier outage (consecutive feature-lookup
  failures) trips the circuit breaker into degraded serving — the epoch
  completes with ``resilience.degraded_lookups > 0`` instead of crashing,
  and a half-open probe closes the breaker once the outage ends;
* **pipeline**: the software-pipelined epoch's crash seam — preempt a
  ``pipeline_depth=1`` run mid-epoch, resume() (the pipelined chunk
  re-issues its carried batch from the seed matrix), and the remaining
  loss trajectory + final params are bit-identical to an UNINTERRUPTED
  SERIAL (depth=0) run — the pipeline survives kill/replay without ever
  serializing in-flight batch state;
* **mutate**: the streaming-mutation drill (quiver_tpu/streaming) — a
  malformed delta batch is quarantined whole at admission (counted,
  never staged), a mid-commit crash (injected at every pre-publish
  stage) leaves the old version readable with SAMPLING BIT-IDENTICAL to
  the pre-commit oracle and the failed commit quarantined not
  half-applied, and a successful commit bumps the version exactly once —
  stale samplers raise until refreshed, then serve the mutated graph;
* **scale-out**: the serving-fleet drill (quiver_tpu/serving/fleet.py) —
  a replica joins MID-TRAFFIC, warms every ladder program from the
  shared persisted AOT-executable cache with ZERO compiles, and serves
  responses bitwise-identical to the already-running replica for the
  same (node, seq) stream (and to the direct single-query oracle);
* **ooc**: the disk-tier drill (quiver_tpu/ooc/) — mid-epoch transient
  disk-read failures are absorbed by the AsyncStager's bounded backoff
  (epoch completes, loss trajectory bit-identical to the fault-free
  disk run), and a TORN raw directory (COMMIT marker missing) raises
  ``CorruptRawDir`` at load, is quarantined aside, and the loader falls
  back to the legacy ``.npz`` of the same topology with sampling
  bit-identical to the original;
* **postmortem**: the flight-recorder drill (quiver_tpu/obs/recorder.py)
  — every fault class above that wires a recorder (nonfinite-guard trip,
  circuit-breaker opening, aborted streaming commit) dumps an
  integrity-verified (CRC-manifested, COMMIT-marker-last) postmortem
  bundle naming the faulting stage (``train``/``gather``/``commit``),
  and a TORN bundle directory is quarantined aside — never trusted —
  while the earlier bundles keep verifying.

Any drill failure raises (the session marks the job failed); success
prints one ``CHAOS <drill> OK`` line per drill. ``--drills`` selects a
subset (the CI smoke runs ``--drills corrupt mutate`` on a 2-device CPU
mesh).

    python -m benchmarks.chaos --smoke
"""

import argparse
import tempfile

import numpy as np

from benchmarks import common

DRILLS = ("guard", "retry", "preempt", "resize", "corrupt", "cold-outage",
          "pipeline", "mutate", "scale-out", "ooc", "postmortem")


def _build_graph(nodes: int, feature_dim: int, seed: int):
    from quiver_tpu import CSRTopo

    rng = np.random.default_rng(seed)
    topo = CSRTopo(
        edge_index=rng.integers(0, nodes, size=(2, 10 * nodes)).astype(
            np.int64
        )
    )
    feat = rng.normal(size=(nodes, feature_dim)).astype(np.float32)
    labels = rng.integers(0, 4, nodes).astype(np.int32)
    return topo, feat, labels


def _build_trainer(topo, feat, local_batch, plan=None, guard=False,
                   checkpoint_dir=None, checkpoint_every=0,
                   pipeline_depth=0, tracer=None, recorder=None):
    import optax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DistributedTrainer

    mesh = make_mesh()  # data = all devices, feature = 1
    sampler = GraphSageSampler(
        topo, [5, 5], seed=3, seed_capacity=local_batch
    )
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir,
                  checkpoint_every=checkpoint_every)
    return DistributedTrainer(
        mesh, sampler, feature, model, optax.sgd(1e-2),
        local_batch=local_batch, nonfinite_guard=guard, fault_plan=plan,
        pipeline_depth=pipeline_depth, tracer=tracer, recorder=recorder,
        **kw
    )


def _tree_equal(a, b) -> bool:
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def drill_guard(topo, feat, labels, local_batch, seed):
    """NaN batch -> cond-skipped update, params preserved, counter = 1."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu import FaultPlan
    from quiver_tpu.obs.registry import GUARD_SKIPPED

    plan = FaultPlan(nan_feature_steps=(1,), nan_rows=8)
    trainer = _build_trainer(topo, feat, local_batch, plan=plan, guard=True)
    params, opt = trainer.init(jax.random.PRNGKey(0))
    lab = jnp.asarray(labels)
    rng = np.random.default_rng(seed)
    for step in range(3):
        p_before = params
        params, opt, loss = trainer.step(
            params, opt, rng.integers(0, topo.node_count,
                                      trainer.global_batch),
            lab, jax.random.PRNGKey(step),
        )
        if step == 1:
            assert not np.isfinite(float(loss)), "poisoned loss was finite"
            assert _tree_equal(params, p_before), \
                "poisoned step mutated params"
            skipped = int(np.asarray(trainer.metrics.value(GUARD_SKIPPED)))
            assert skipped == 1, f"skip counter {skipped} != 1"
        else:
            assert np.isfinite(float(loss)), f"clean step {step} loss NaN"
    common.write_metrics(trainer, drill="chaos-guard")
    common.log("CHAOS guard OK (poisoned step skipped, params preserved)")


def drill_retry(topo, steps, local_batch, seed):
    """Seeded transient sampler faults -> retried, stream bit-identical."""
    from quiver_tpu import FaultPlan, GraphSageSampler
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.parallel.pipeline import Prefetcher

    plan = FaultPlan.chaos(
        seed=seed, steps=steps, transient_p=0.4, max_transient=2
    )
    if not plan.sampler_faults:
        # a sparse draw must not turn the drill into a no-op
        import dataclasses

        plan = dataclasses.replace(plan, sampler_faults={1: 2})
    seeds = [
        np.random.default_rng(seed + i).integers(
            0, topo.node_count, local_batch
        )
        for i in range(steps)
    ]
    oracle = GraphSageSampler(topo, [5, 5], seed=3,
                              seed_capacity=local_batch)
    clean = [oracle.sample(s) for s in seeds]
    faulty = plan.wrap_sampler(
        GraphSageSampler(topo, [5, 5], seed=3, seed_capacity=local_batch)
    )
    timeline = StepTimeline()
    pf = Prefetcher(faulty, None, depth=2, retries=3, backoff=1e-3,
                    timeline=timeline)
    batches = list(pf.run(seeds))
    assert len(batches) == steps, f"{len(batches)}/{steps} delivered"
    planned = sum(plan.sampler_faults.values())
    assert pf.retries_total == planned, \
        f"retries {pf.retries_total} != planned {planned}"
    for c, b in zip(clean, batches):
        assert np.array_equal(np.asarray(c.n_id), np.asarray(b.out.n_id)), \
            "recovered stream diverged from the fault-free oracle"
    common.log(
        f"CHAOS retry OK ({planned} transient faults absorbed, stream "
        "bit-identical)"
    )


def drill_preempt_resume(topo, feat, labels, local_batch, seed):
    """Kill at a planned step, resume, compare the trajectory bitwise."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu import FaultPlan, Preemption

    lab = jnp.asarray(labels)
    idx = np.random.default_rng(seed).integers(
        0, topo.node_count, 6 * local_batch * jax.device_count()
    )
    with tempfile.TemporaryDirectory() as tmp:
        trainer_a = _build_trainer(
            topo, feat, local_batch, checkpoint_dir=f"{tmp}/a",
            checkpoint_every=2,
        )
        seed_mat = trainer_a.pack_epoch(idx, seed=0)
        key = jax.random.PRNGKey(7)
        pa, oa = trainer_a.init(jax.random.PRNGKey(0))
        pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, lab, key)
        losses_a = np.asarray(losses_a)

        trainer_b = _build_trainer(
            topo, feat, local_batch, checkpoint_dir=f"{tmp}/b",
            checkpoint_every=2, plan=FaultPlan(preempt_at_step=3),
        )
        p0, o0 = trainer_b.init(jax.random.PRNGKey(0))
        preempted = False
        try:
            trainer_b.epoch_scan(p0, o0, seed_mat, lab, key)
        except Preemption:
            preempted = True
        assert preempted, "FaultPlan preemption never fired"
        pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0)
        assert step == 2, f"resumed at step {step}, expected 2"
        pr, orr, losses_r = trainer_b.epoch_scan(
            pr, orr, seed_mat, lab, key_r, epoch=epoch, start_step=step
        )
        losses_r = np.asarray(losses_r)
        assert np.array_equal(
            losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
        ), "resumed loss trajectory diverged"
        assert _tree_equal(pa, pr), "resumed final params diverged"
        trainer_a.checkpointer.close()
        trainer_b.checkpointer.close()
    common.log(
        f"CHAOS preempt/resume OK (killed at step 3, resumed at {step}, "
        f"{losses_r.shape[0]} remaining steps bit-identical)"
    )


def _build_elastic_trainer(topo, feat, mesh, local_batch, workers,
                           checkpoint_dir=None, checkpoint_every=2,
                           plan=None):
    """Elastic config: mesh-sharded topology + three-tier sharded feature
    + logical_workers (the resize drill's trainer shape)."""
    import optax

    from quiver_tpu import GraphSageSampler
    from quiver_tpu.feature.shard import ShardedFeature
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.mesh import FEATURE_AXIS
    from quiver_tpu.parallel.trainer import DistributedTrainer

    n, d = feat.shape
    F = mesh.shape[FEATURE_AXIS]
    store = ShardedFeature(
        mesh,
        device_cache_size=max(n // (2 * F), 1) * d * feat.dtype.itemsize,
        replicate_budget=8 * d * feat.dtype.itemsize,
        csr_topo=topo,
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(
        topo, [5, 5], seed=3, seed_capacity=local_batch,
        topo_sharding="mesh", mesh=mesh,
    )
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir,
                  checkpoint_every=checkpoint_every)
    return DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2),
        local_batch=local_batch, seed_sharding="all",
        logical_workers=workers, fault_plan=plan, **kw
    )


def drill_resize(topo, feat, labels, local_batch, seed):
    """Kill at F, resume(mesh=F/2): trajectory + params bit-identical."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu import FaultPlan, Preemption
    from quiver_tpu.parallel.mesh import make_mesh

    F = jax.device_count()
    if F % 2 or F < 2:
        common.log(
            f"CHAOS resize SKIPPED ({F} devices; needs an even count >= 2)"
        )
        return
    lab = jnp.asarray(labels)
    mesh_hi = make_mesh(n_devices=F, data=1, feature=F)
    idx = np.random.default_rng(seed).integers(
        0, topo.node_count, 6 * local_batch * F
    )
    with tempfile.TemporaryDirectory() as tmp:
        trainer_a = _build_elastic_trainer(
            topo, feat, mesh_hi, local_batch, F, checkpoint_dir=f"{tmp}/a",
        )
        seed_mat = trainer_a.pack_epoch(idx, seed=0)
        key = jax.random.PRNGKey(7)
        pa, oa = trainer_a.init(jax.random.PRNGKey(0))
        pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, lab, key)
        losses_a = np.asarray(losses_a)

        trainer_b = _build_elastic_trainer(
            topo, feat, mesh_hi, local_batch, F, checkpoint_dir=f"{tmp}/b",
            plan=FaultPlan(preempt_at_step=3),
        )
        p0, o0 = trainer_b.init(jax.random.PRNGKey(0))
        try:
            trainer_b.epoch_scan(p0, o0, seed_mat, lab, key)
            raise AssertionError("FaultPlan preemption never fired")
        except Preemption:
            pass
        mesh_lo = make_mesh(n_devices=F // 2, data=1, feature=F // 2)
        pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0, mesh=mesh_lo)
        assert trainer_b.blocks_per_device == 2, \
            f"blocks/device {trainer_b.blocks_per_device} != 2"
        pr, orr, losses_r = trainer_b.epoch_scan(
            pr, orr, seed_mat, lab, key_r, epoch=epoch, start_step=step
        )
        losses_r = np.asarray(losses_r)
        assert np.array_equal(
            losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
        ), "resized loss trajectory diverged from the full-mesh run"
        assert _tree_equal(pa, pr), "resized final params diverged"
        trainer_a.checkpointer.close()
        trainer_b.checkpointer.close()
    common.log(
        f"CHAOS resize OK (killed at step 3 on F={F}, resumed at step "
        f"{step} on F={F // 2}, {losses_r.shape[0]} remaining steps "
        "bit-identical)"
    )


def drill_pipeline(topo, feat, labels, local_batch, seed):
    """Preempt a pipeline_depth=1 epoch mid-flight, resume, and compare
    the remaining trajectory + final params bitwise against an
    UNINTERRUPTED SERIAL (depth=0) run — the crash/replay seam composes
    with the one-step skew because pipelined chunks re-issue their
    carried batch from the seed matrix instead of serializing it."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu import FaultPlan, Preemption
    from quiver_tpu.obs.registry import PIPELINE_REISSUES

    lab = jnp.asarray(labels)
    idx = np.random.default_rng(seed).integers(
        0, topo.node_count, 6 * local_batch * jax.device_count()
    )
    with tempfile.TemporaryDirectory() as tmp:
        trainer_a = _build_trainer(topo, feat, local_batch)
        seed_mat = trainer_a.pack_epoch(idx, seed=0)
        key = jax.random.PRNGKey(7)
        pa, oa = trainer_a.init(jax.random.PRNGKey(0))
        pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, lab, key)
        losses_a = np.asarray(losses_a)

        trainer_b = _build_trainer(
            topo, feat, local_batch, checkpoint_dir=f"{tmp}/b",
            checkpoint_every=2, plan=FaultPlan(preempt_at_step=3),
            pipeline_depth=1,
        )
        p0, o0 = trainer_b.init(jax.random.PRNGKey(0))
        preempted = False
        try:
            trainer_b.epoch_scan(p0, o0, seed_mat, lab, key)
        except Preemption:
            preempted = True
        assert preempted, "FaultPlan preemption never fired"
        pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0)
        assert step == 2, f"resumed at step {step}, expected 2"
        pr, orr, losses_r = trainer_b.epoch_scan(
            pr, orr, seed_mat, lab, key_r, epoch=epoch, start_step=step
        )
        losses_r = np.asarray(losses_r)
        assert np.array_equal(
            losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
        ), "resumed pipelined trajectory diverged from the serial oracle"
        assert _tree_equal(pa, pr), "resumed pipelined params diverged"
        reissues = int(np.asarray(
            trainer_b.metrics.value(PIPELINE_REISSUES)
        ))
        assert reissues > 0, "chunked pipelined run never re-issued"
        trainer_b.checkpointer.close()
    common.log(
        f"CHAOS pipeline OK (depth=1 killed at step 3, resumed at {step}, "
        f"{losses_r.shape[0]} remaining steps bit-identical to the serial "
        f"run, {reissues} chunk re-issues)"
    )


def drill_corrupt_checkpoint(topo, feat, labels, local_batch, seed):
    """Flip manifest-covered bytes in the newest checkpoint: resume()
    quarantines it (and a planted uncommitted dir) and falls back."""
    import glob
    import os

    import jax
    import jax.numpy as jnp

    lab = jnp.asarray(labels)
    idx = np.random.default_rng(seed).integers(
        0, topo.node_count, 6 * local_batch * jax.device_count()
    )
    with tempfile.TemporaryDirectory() as tmp:
        ckdir = f"{tmp}/ck"
        trainer = _build_trainer(
            topo, feat, local_batch, checkpoint_dir=ckdir, checkpoint_every=2
        )
        seed_mat = trainer.pack_epoch(idx, seed=0)
        key = jax.random.PRNGKey(7)
        p0, o0 = trainer.init(jax.random.PRNGKey(0))
        trainer.epoch_scan(p0, o0, seed_mat, lab, key)
        trainer.checkpointer.wait_until_finished()
        newest = trainer.checkpointer.latest_step()
        prev_valid = trainer.checkpointer.all_steps()[-2]
        # flip a manifest-covered byte in the newest payload
        apath = os.path.join(ckdir, f"step-{newest}", "arrays.bin")
        with open(apath, "r+b") as fh:
            fh.seek(os.path.getsize(apath) // 2)
            b = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([b[0] ^ 0xFF]))
        # plant an uncommitted partial directory "newer" than everything
        partial = os.path.join(ckdir, f"step-{newest + 50}")
        os.makedirs(partial)
        with open(os.path.join(partial, "arrays.bin"), "wb") as fh:
            fh.write(b"\x00" * 16)  # no manifest, no COMMIT: a crashed save
        assert trainer.checkpointer.latest_step() == newest, \
            "uncommitted directory leaked into the step scan"
        pr, orr, key_r, step, epoch = trainer.resume(p0, o0)
        meta = trainer.checkpointer.metadata(prev_valid)
        assert step == meta["step"], \
            f"fell back to step {step}, expected {meta['step']}"
        quarantined = glob.glob(os.path.join(ckdir, "quarantine-*"))
        assert quarantined, "corrupt checkpoint was not quarantined"
        # the run continues from the fallback without manual intervention
        pr, orr, losses_r = trainer.epoch_scan(
            pr, orr, seed_mat, lab, key_r, epoch=epoch, start_step=step
        )
        assert np.isfinite(np.asarray(losses_r)).all()
        trainer.checkpointer.close()
    common.log(
        f"CHAOS corrupt-checkpoint OK (newest checkpoint poisoned + "
        f"partial dir planted; auto-fell-back to step {step}, "
        f"{np.asarray(losses_r).shape[0]} steps completed after)"
    )


def drill_cold_outage(topo, feat, labels, local_batch, seed):
    """Cold-tier outage: the circuit breaker serves fallback rows, the
    epoch completes, degraded_lookups > 0, breaker closes after."""
    import jax
    import optax

    from quiver_tpu import (
        DegradedFeature,
        FaultPlan,
        Feature,
        GraphSageSampler,
    )
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.obs.registry import DEGRADED_LOOKUPS
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    mesh = make_mesh()  # data = all devices, feature = 1
    store = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    # outage: 6 consecutive lookup failures starting at lookup 3 (the
    # init lookup is 0); breaker opens after 3, probes every 2 calls
    plan = FaultPlan(feature_faults={3: 6})
    degraded = DegradedFeature(
        plan.wrap_feature(store), failures=3, probe_every=2,
        fallback="zeros",
    )
    sampler = GraphSageSampler(
        topo, [5, 5], seed=3, seed_capacity=local_batch
    )
    trainer = DataParallelTrainer(
        mesh, sampler, degraded,
        GraphSAGE(hidden=16, num_classes=4, num_layers=2),
        optax.sgd(1e-2), local_batch=local_batch, prefetch_retries=3,
        prefetch_backoff=1e-3,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    idx = np.random.default_rng(seed).integers(
        0, topo.node_count, 10 * trainer.global_batch
    )
    params, opt, mean_loss, steps = trainer.train_epoch(
        params, opt, idx, np.asarray(labels), jax.random.PRNGKey(1)
    )
    assert steps == 10, f"epoch delivered {steps}/10 steps"
    assert np.isfinite(mean_loss), "degraded epoch produced NaN mean loss"
    served = int(np.asarray(degraded.metrics.value(DEGRADED_LOOKUPS)))
    assert served > 0 and degraded.degraded_total == served, \
        f"degraded_lookups {served} (expected > 0)"
    assert degraded.breaker.state == "closed", \
        f"breaker ended {degraded.breaker.state!r} (outage was finite)"
    common.write_metrics(degraded, trainer, drill="chaos-cold-outage")
    common.log(
        f"CHAOS cold-outage OK ({served} lookups served degraded, epoch "
        f"completed {steps}/10 steps, breaker closed after the outage)"
    )


def drill_postmortem(topo, feat, labels, local_batch, seed):
    """Every chaos fault class dumps an integrity-verified postmortem
    bundle naming the faulting stage — guard trip (train), breaker open
    (gather), aborted streaming commit (commit) — and a torn bundle
    directory is quarantined, never trusted, while the earlier bundles
    keep verifying."""
    import os

    import jax
    import jax.numpy as jnp

    from quiver_tpu import (
        CommitAborted,
        CSRTopo,
        DegradedFeature,
        DeltaBatch,
        FaultPlan,
        Feature,
        FlightRecorder,
        StreamingGraph,
        Tracer,
        TransientFault,
    )
    from quiver_tpu.obs.recorder import TornBundle, list_bundles, \
        verify_bundle

    rng = np.random.default_rng(seed)
    n = topo.node_count
    with tempfile.TemporaryDirectory() as tmp:
        tracer = Tracer()
        rec = FlightRecorder(tmp, capacity=64, keep=8, tracer=tracer)

        # fault class 1 — nonfinite-guard trip names stage "train"
        plan = FaultPlan(nan_feature_steps=(1,), nan_rows=8)
        trainer = _build_trainer(topo, feat, local_batch, plan=plan,
                                 guard=True, tracer=tracer, recorder=rec)
        params, opt = trainer.init(jax.random.PRNGKey(0))
        lab = jnp.asarray(labels)
        for step in range(2):
            params, opt, _loss = trainer.step(
                params, opt, rng.integers(0, n, trainer.global_batch),
                lab, jax.random.PRNGKey(step),
            )

        # fault class 2 — the breaker opening names stage "gather"
        store = Feature(device_cache_size="1G").from_cpu_tensor(feat)
        degraded = DegradedFeature(
            FaultPlan(feature_faults={0: 5}).wrap_feature(store),
            failures=3, probe_every=2, fallback="zeros", recorder=rec,
        )
        ids = rng.integers(0, n, 4)
        for _ in range(2):  # closed breaker propagates the outage
            try:
                degraded[ids]
                raise AssertionError("closed breaker swallowed the fault")
            except TransientFault:
                pass
        degraded[ids]  # third consecutive failure opens it -> bundle
        assert degraded.breaker.state == "open", degraded.breaker.state

        # fault class 3 — an aborted streaming commit names stage "commit"
        sg = StreamingGraph(
            CSRTopo(indptr=topo.indptr, indices=topo.indices),
            recorder=rec,
        )
        assert sg.ingest(DeltaBatch(
            edge_inserts=rng.integers(0, n, size=(2, 8))
        )), "good delta batch rejected"
        try:
            sg.commit(inject_failure="merge")
            raise AssertionError("injected commit failure did not abort")
        except CommitAborted:
            pass

        stages = {m["reason"]: m["stage"] for _p, m in rec.bundles()}
        want = {"nonfinite_guard": "train", "breaker_open": "gather",
                "commit_abort": "commit"}
        for reason, stage in want.items():
            assert stages.get(reason) == stage, \
                f"{reason}: stage {stages.get(reason)!r} != {stage!r}"
        for path, _m in rec.bundles():
            verify_bundle(path)  # raises TornBundle on any corruption

        # fault class 4 — a torn dump is quarantined, never trusted
        torn = rec.trigger("torn_drill", stage="train",
                           inject_failure="torn")
        try:
            verify_bundle(torn)
            raise AssertionError("torn bundle passed verification")
        except TornBundle:
            pass
        survivors = list_bundles(tmp, quarantine=True)
        assert len(survivors) == len(want), \
            f"{len(survivors)} bundles survived, expected {len(want)}"
        assert any(name.startswith("quarantine-")
                   for name in os.listdir(tmp)), "torn dir not quarantined"
        for path, _m in survivors:
            verify_bundle(path)  # quarantine left the good bundles intact
        common.log(
            f"CHAOS postmortem OK ({len(want)} fault classes bundled + "
            "verified, torn dir quarantined)"
        )


def drill_scale_out(topo, feat, seed):
    """Serving-fleet scale-out: a replica joining mid-traffic warms from
    the shared AOT cache (zero compiles) and answers the same
    (node, seq) stream bitwise-identically to the running replica."""
    import jax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.train import empty_adjs, init_model
    from quiver_tpu.serving import ServingFleet

    rng = np.random.default_rng(seed)
    n = topo.node_count
    d = feat.shape[1]
    store = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [4, 3], seed=3)
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    adjs = empty_adjs([4, 3], batch=4, node_count=n)
    params = init_model(
        model, jax.random.PRNGKey(seed),
        np.zeros((adjs[0].size[0], d), np.float32), adjs,
    )

    with tempfile.TemporaryDirectory() as tmp:
        fleet = ServingFleet(
            sampler, model, params, store, replicas=1,
            aot_cache=f"{tmp}/aot", seed=5, max_batch=2,
        )
        first = fleet.cold_starts[0]
        assert first["compiled"] > 0 and first["loaded"] == 0, first
        nodes = rng.integers(0, n, 12)
        out0 = fleet.servers[0].serve(nodes)  # traffic before the join

        joiner = fleet.add_replica()  # joins mid-traffic
        join = fleet.cold_starts[-1]
        assert join["compiled"] == 0, f"join compiled programs: {join}"
        assert join["loaded"] == first["compiled"], (join, first)
        assert joiner.recompiles == 0, joiner.recompiles

        # replay the same node stream on the joiner: its batcher starts
        # at seq 0 exactly like replica 0 did, so the (node, seq) pairs
        # match and (shared base seed) responses must be bitwise equal
        out1 = joiner.serve(nodes)
        for a, b in zip(out0, out1):
            assert (a.node, a.seq) == (b.node, b.seq), (a, b)
            assert np.array_equal(a.result, b.result), \
                f"replica divergence at (node={a.node}, seq={a.seq})"
            assert np.array_equal(b.result, fleet.oracle(b.node, b.seq)), \
                f"oracle divergence at (node={b.node}, seq={b.seq})"

        # the grown fleet keeps serving mixed-class traffic compile-free
        fleet.serve(rng.integers(0, n, 8), priority="bronze")
        assert fleet.recompiles == first["compiled"], \
            (fleet.recompiles, first)
    common.log(
        f"CHAOS scale-out OK (mid-traffic join warmed {join['loaded']} "
        f"programs from the shared AOT cache with 0 compiles; "
        f"{len(nodes)} (node, seq) responses bitwise-identical across "
        f"replicas and vs the oracle)"
    )


def drill_mutate(topo_seed_graph, feat, local_batch, seed):
    """Malformed-delta quarantine; mid-commit crash at every pre-publish
    stage leaves the old version readable and sampling bit-identical;
    a published commit invalidates stale samplers exactly once."""
    import jax

    from quiver_tpu import (
        CommitAborted,
        CSRTopo,
        DeltaBatch,
        GraphSageSampler,
        StreamingGraph,
        VersionMismatchError,
    )
    from quiver_tpu.feature.shard import ShardedFeature
    from quiver_tpu.obs.registry import DELTAS_QUARANTINED
    from quiver_tpu.parallel.mesh import FEATURE_AXIS, make_mesh

    F = jax.device_count()
    mesh = make_mesh(n_devices=F, data=1, feature=F)
    # a fresh topology: the drill mutates it, the shared one must survive
    rng = np.random.default_rng(seed)
    n = topo_seed_graph.node_count
    topo = CSRTopo(indptr=topo_seed_graph.indptr,
                   indices=topo_seed_graph.indices)
    d = feat.shape[1]
    store = ShardedFeature(
        mesh, device_cache_size=max(n // (2 * F), 1) * d * feat.dtype.itemsize,
        replicate_budget=8 * d * feat.dtype.itemsize, csr_topo=topo,
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [5, 5], seed=3,
                               seed_capacity=local_batch,
                               topo_sharding="mesh", mesh=mesh)
    sg = StreamingGraph(topo, feature=store)
    seeds = rng.integers(0, n, local_batch * F)
    key = jax.random.PRNGKey(11)
    oracle = sampler.sample(seeds, key=key)

    # 1. malformed batches: quarantined whole, never staged
    rejects = (
        DeltaBatch(edge_inserts=np.array([[0], [n + 7]]), tag="oob"),
        DeltaBatch(update_ids=np.array([1]),
                   update_rows=np.full((1, d), np.nan, np.float32),
                   tag="nan-row"),
        DeltaBatch(edge_inserts=np.array([[2, 2], [3, 3]]), tag="dup"),
    )
    for bad in rejects:
        assert not sg.ingest(bad), f"malformed batch {bad.tag} was staged"
    q = int(np.asarray(sg.metrics.value(DELTAS_QUARANTINED)))
    assert q == len(rejects), f"quarantine counter {q} != {len(rejects)}"
    assert not sg.staged

    # 2. mid-commit crash at every pre-publish stage: old version stays
    # readable and sampling is bit-identical to the pre-commit oracle
    live_src = int(np.repeat(
        np.arange(n), topo.degree)[0])  # a row with at least one edge
    live_dst = int(np.asarray(topo.indices)[
        np.asarray(topo.indptr, dtype=np.int64)[live_src]])
    good = DeltaBatch(
        edge_inserts=rng.integers(0, n, size=(2, 8)),
        edge_deletes=np.array([[live_src], [live_dst]]),
        update_ids=np.array([0, n // 2]),
        update_rows=rng.normal(size=(2, d)).astype(np.float32),
    )
    for stage in ("merge", "verify", "features"):
        assert sg.ingest(good), f"good batch rejected before {stage}"
        try:
            sg.commit(inject_failure=stage)
            raise AssertionError(f"injected {stage} failure did not abort")
        except CommitAborted:
            pass
        assert topo.version == 0 and store.version == 0, \
            f"crash at {stage} leaked a version bump"
        assert not sg.staged, f"crash at {stage} left batches staged"
        replay = sampler.sample(seeds, key=key)
        assert np.array_equal(np.asarray(oracle.n_id),
                              np.asarray(replay.n_id)), \
            f"sampling diverged after aborted commit at {stage}"

    # 3. a real commit publishes once; stale sampler raises, refreshed
    # sampler serves the mutated graph
    assert sg.ingest(good)
    res = sg.commit()
    assert res.version == 1 and topo.version == 1 and store.version == 1
    try:
        sampler.sample(seeds, key=key)
        raise AssertionError("stale sampler did not raise after commit")
    except VersionMismatchError:
        pass
    sampler.refresh_topology()
    out = sampler.sample(seeds, key=key)
    assert out.n_id.shape == oracle.n_id.shape
    updated = np.asarray(store.gather(good.update_ids))
    assert np.array_equal(updated, good.update_rows), \
        "committed row updates not served"
    common.log(
        f"CHAOS mutate OK ({len(rejects)} malformed batches quarantined; "
        f"3 mid-commit crashes rolled back bit-identically; commit v1 "
        f"published +{res.edges_inserted}/-{res.edges_deleted} edges, "
        f"{res.rows_updated} row updates, stale sampler raised then "
        f"refreshed)"
    )


def drill_ooc(topo_shared, feat, labels, local_batch, seed):
    """Disk-tier chaos: transient read faults mid-epoch are retried by
    the AsyncStager's backoff (trajectory bit-identical to the
    fault-free disk run); a torn raw dir is quarantined and the loader
    falls back to the legacy .npz with sampling bit-identical."""
    import os

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.ooc import (
        CorruptRawDir,
        MmapFeatureStore,
        quarantine_raw_dir,
    )
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    # private topology: the store's degree reorder writes feature_order,
    # which must not leak into the other drills' shared graph
    topo = CSRTopo(indptr=topo_shared.indptr, indices=topo_shared.indices)
    n, d = feat.shape
    lab = jnp.asarray(labels)
    idx = np.random.default_rng(seed).integers(
        0, n, 6 * local_batch * jax.device_count()
    )

    with tempfile.TemporaryDirectory() as tmp:
        rows = os.path.join(tmp, "rows")
        MmapFeatureStore.write(
            rows, feat, device_cache_size=max(n // 5, 1) * d * 4,
            csr_topo=topo,
        )

        def run_epoch(inject_faults):
            store = MmapFeatureStore(rows, window_rows=16, cache_windows=8,
                                     retries=3, backoff=1e-3)
            injected = set()
            if inject_faults:
                real = store.stager._read_window

                def flaky(window):
                    # first read of the first 3 distinct windows fails
                    # once; the stager's backoff re-read succeeds
                    if len(injected) < 3 and window not in injected:
                        injected.add(window)
                        raise OSError(
                            f"injected disk fault on window {window}"
                        )
                    return real(window)

                store.stager._read_window = flaky
            sampler = GraphSageSampler(topo, [5, 5], seed=3,
                                       seed_capacity=local_batch)
            trainer = DataParallelTrainer(
                make_mesh(), sampler, store,
                GraphSAGE(hidden=16, num_classes=4, num_layers=2),
                optax.sgd(1e-2), local_batch=local_batch,
            )
            params, opt = trainer.init(jax.random.PRNGKey(0))
            params, opt, loss, steps = trainer.train_epoch(
                params, opt, idx, lab, jax.random.PRNGKey(1),
                rng=np.random.default_rng(seed),
            )
            retries = store.stager.read_retries_total
            store.close()
            return float(loss), int(steps), retries, len(injected)

        clean_loss, clean_steps, _, _ = run_epoch(False)
        loss, steps, retries, injected = run_epoch(True)
        assert injected == 3, f"only {injected}/3 faults injected"
        assert retries == injected, \
            f"stager retries {retries} != {injected} injected faults"
        assert steps == clean_steps, f"epoch delivered {steps}/{clean_steps}"
        assert loss == clean_loss, \
            "recovered epoch diverged from the fault-free disk run"

        # torn publish: COMMIT marker missing -> quarantine + npz fallback
        raw = os.path.join(tmp, "topo.raw")
        npz = os.path.join(tmp, "topo.npz")
        topo.save(raw, format="raw")
        topo.save(npz)
        os.remove(os.path.join(raw, "COMMIT"))
        torn = False
        try:
            CSRTopo.load(raw, mmap=True)
        except CorruptRawDir:
            torn = True
            quarantine_raw_dir(raw)
            recovered = CSRTopo.load(npz)
        assert torn, "torn raw dir loaded without complaint"
        assert not os.path.exists(raw), "torn raw dir not quarantined"
        # fresh same-seed samplers: first draws are deterministic, so the
        # fallback topology must reproduce the original stream bitwise
        seeds = np.random.default_rng(seed).integers(0, n, local_batch)
        a = GraphSageSampler(topo, [5, 5], seed=3,
                             seed_capacity=local_batch).sample(seeds)
        b = GraphSageSampler(recovered, [5, 5], seed=3,
                             seed_capacity=local_batch).sample(seeds)
        assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id)), \
            "sampling off the npz fallback diverged from the original"
    common.log(
        f"CHAOS ooc OK ({retries} mid-epoch disk faults retried, epoch "
        f"{steps}/{clean_steps} steps bit-identical to fault-free; torn "
        "raw dir quarantined, npz fallback sampling bit-identical)"
    )


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=2000)
    p.add_argument("--feature-dim", type=int, default=16)
    p.add_argument("--local-batch", type=int, default=16)
    p.add_argument("--retry-steps", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--drills", nargs="*", default=None, choices=DRILLS,
                   help="subset of drills to run (default: all)")
    p.add_argument("--smoke", action="store_true",
                   help="shrink the drills further (rehearsal mode)")
    args = p.parse_args()
    if args.smoke:
        args.nodes = min(args.nodes, 800)
        args.retry_steps = min(args.retry_steps, 4)

    common.init_backend()
    topo, feat, labels = _build_graph(
        args.nodes, args.feature_dim, args.seed
    )
    selected = tuple(args.drills) if args.drills else DRILLS

    def body():
        if "guard" in selected:
            drill_guard(topo, feat, labels, args.local_batch, args.seed)
        if "retry" in selected:
            drill_retry(topo, args.retry_steps, args.local_batch, args.seed)
        if "preempt" in selected:
            drill_preempt_resume(
                topo, feat, labels, args.local_batch, args.seed
            )
        if "resize" in selected:
            drill_resize(topo, feat, labels, args.local_batch, args.seed)
        if "corrupt" in selected:
            drill_corrupt_checkpoint(
                topo, feat, labels, args.local_batch, args.seed
            )
        if "cold-outage" in selected:
            drill_cold_outage(
                topo, feat, labels, args.local_batch, args.seed
            )
        if "pipeline" in selected:
            drill_pipeline(topo, feat, labels, args.local_batch, args.seed)
        if "mutate" in selected:
            drill_mutate(topo, feat, args.local_batch, args.seed)
        if "scale-out" in selected:
            drill_scale_out(topo, feat, args.seed)
        if "ooc" in selected:
            drill_ooc(topo, feat, labels, args.local_batch, args.seed)
        if "postmortem" in selected:
            drill_postmortem(
                topo, feat, labels, args.local_batch, args.seed
            )
        common.log(f"CHAOS all drills passed ({', '.join(selected)})")
        return 0

    return common.run_guarded(body, args)


if __name__ == "__main__":
    raise SystemExit(main())
