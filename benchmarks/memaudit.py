"""memory-audit lane: the graftmem gate as a scoreboard job.

Runs the ``mem`` rule family (peak-hbm-budget, no-silent-replication,
vmem-budget, padding-waste) over the full program registry on the
2-device CPU audit mesh — trace/lower only, no step executes — then
prints the per-target budget table and emits one headline record:

* ``memaudit-min-headroom`` — the tightest target's remaining budget
  fraction (``headroom / hbm_budget``). The gate fails (nonzero exit)
  on ANY graftmem finding, on an unpriced target, or when a target no
  longer fits its declared budget per ``CostModel.predict_hbm`` — the
  same surface the controller consults, so the job proves the wiring,
  not just the table.

``--xla`` additionally compiles every target and joins XLA's
``memory_analysis()`` peaks as a cross-check column (the only compiling
path in the auditor; the CI memory-audit job runs it, the default
scoreboard row skips it for wall-clock).

    python -m benchmarks.memaudit [--xla] [--targets a,b]
"""

from __future__ import annotations

import argparse

from benchmarks import common


def _audit_rows(args):
    from quiver_tpu.control.cost import CostModel
    from quiver_tpu.tools.audit.mem import format_peak_table, peak_table
    from quiver_tpu.tools.audit.runner import run_audit

    names = ([n.strip() for n in args.targets.split(",") if n.strip()]
             if args.targets else None)
    result = run_audit(select=["mem"], targets=names)
    for f in result.findings:
        common.log(f"MEMAUDIT finding: {f.target}: {f.rule}: {f.message}")
    if result.findings or result.exit_code != 0:
        raise SystemExit(1)

    rows = peak_table(names, with_xla=args.xla)
    for line in format_peak_table(rows).splitlines():
        common.log(line)

    # the controller-facing wiring: the same peaks feed CostModel and
    # every target must come back as fitting its declared budget
    model = CostModel(local_len=1, num_shards=1)
    model.calibrate_hbm({r["target"]: r["peak_bytes"] for r in rows})
    misfit = [r["target"] for r in rows
              if r["hbm_budget"] is None
              or not model.predict_hbm(r["target"],
                                       r["hbm_budget"])["fits"]]
    if misfit:
        common.log(f"MEMAUDIT over budget / unpriced: {misfit}")
        raise SystemExit(1)
    return rows


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--xla", action="store_true",
                   help="compile each target and join XLA "
                        "memory_analysis() as a cross-check column")
    p.add_argument("--targets", default=None,
                   help="comma-separated registry subset (default: all)")
    p.add_argument("--smoke", action="store_true",
                   help="accepted for harness parity; the audit is "
                        "already trace-only and CPU-pinned")
    args = p.parse_args()

    # the audit mesh is 2 forced host devices — pin BEFORE any jax
    # backend init (a no-op if the process already chose a backend)
    from quiver_tpu.tools.audit.cli import _pin_platform

    _pin_platform()

    def body():
        rows = _audit_rows(args)
        fracs = {r["target"]: r["headroom_bytes"] / r["hbm_budget"]
                 for r in rows}
        tightest = min(fracs, key=fracs.get)
        extras = {
            "targets_audited": len(rows),
            "findings": 0,
            "tightest_target": tightest,
            "est_peak_total_bytes": sum(r["peak_bytes"] for r in rows),
        }
        if args.xla:
            ratios = [r["xla_ratio"] for r in rows
                      if r.get("xla_ratio") is not None]
            if ratios:
                extras["xla_ratio_min"] = min(ratios)
                extras["xla_ratio_max"] = max(ratios)
        common.emit("memaudit-min-headroom", fracs[tightest], "frac",
                    None, **extras)
        return 0

    return common.run_guarded(body, args)


if __name__ == "__main__":
    raise SystemExit(main())
