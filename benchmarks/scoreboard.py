"""One-command TPU scoreboard: run every headline benchmark, write the
results table (VERDICT r2 items 2-3).

Runs each benchmark as a supervised subprocess (same discipline as the
repo-root ``bench.py``: hard timeout, JSON harvested from stdout, failures
recorded instead of propagated) and writes:

* ``docs/TPU_RESULTS.md`` — the scoreboard table, every row stamped with
  its platform, vs the reference's published numbers (BASELINE.md);
* ``docs/tpu_results.json`` — the raw records;
* ``BENCH_TRAJECTORY.jsonl`` (repo root) — one consolidated record per
  round, appended, never rewritten: round-over-round movement of every
  headline metric survives even when the per-round table is regenerated
  whole. ``--backfill-trajectory`` reconstructs the early rounds from the
  archived ``BENCH_r0*.json`` supervisor captures.

    python -m benchmarks.scoreboard                 # full run
    python -m benchmarks.scoreboard --smoke         # small shapes
    python -m benchmarks.scoreboard --only sampler-hbm feature-replicate
    python -m benchmarks.scoreboard --backfill-trajectory

A row whose ``platform`` is not ``tpu`` means the chip was unreachable for
that run; re-run when it frees up. The table is regenerated whole each time.
"""

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAJECTORY = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")

# (key, module, args, baseline note)
JOBS = [
    # ordered: highest-evidence rows first, so a short chip window still
    # lands the headline stream/scan numbers before the long-tail jobs
    ("sampler-hbm", "benchmarks.bench_sampler",
     ["--mode", "HBM", "--stream", "128", "--dedup", "both"],
     "ref 34.29M SEPS (1-GPU UVA, Introduction_en.md:41); sort, dense-map "
     "AND scan dedup measured, fastest first (stage profile split into "
     "its own job — one monolithic first job cost r4 a whole window)"),
    ("primitives", "benchmarks.microbench", [],
     "sort/scatter/gather/cummax Melem/s — decides which dedup strategy "
     "SHOULD win on this chip (scatter-serialization diagnosis), ~2 min"),
    ("feature-replicate", "benchmarks.bench_feature",
     ["--policy", "replicate", "--stream", "32"],
     "ref 14.82 GB/s (1 GPU, 20% cache, Introduction_en.md:95)"),
    ("epoch-scan", "benchmarks.bench_epoch",
     ["--scan-epoch", "--bf16", "--cache-ratio", "1.0"],
     "whole epoch as ONE compiled program, bf16 — the TPU-native epoch "
     "loop, measured directly (vs ref 11.1 s, Introduction_en.md:146-149)"),
    ("epoch-pipelined", "benchmarks.bench_epoch",
     ["--pipeline", "--cache-ratio", "1.0"],
     "software-pipelined epoch (one-step skew: batch t+1's sample+gather "
     "under batch t's fwd/bwd, bitwise-identical losses) — serial "
     "stage-sum, Prefetcher, serial-scan, and pipelined rows from ONE "
     "invocation; overlap_efficiency > 1.0 and recompiles_steady = 0 "
     "are the acceptance gates"),
    ("sampler-host", "benchmarks.bench_sampler",
     ["--mode", "HOST", "--stream", "128"],
     "ref 34.29M SEPS; ref GPU-over-UVA delta +30-40% (:45)"),
    ("sampler-pallas", "benchmarks.bench_sampler",
     ["--mode", "HBM", "--kernel", "pallas", "--stream", "128"],
     "windowed Pallas kernel vs the XLA row above"),
    ("sampler-fused-pallas", "benchmarks.bench_sampler",
     ["--mode", "HBM", "--kernel", "fused", "--weighted", "--stream",
      "128", "--stages"],
     "fused sample megakernel on the weighted inverse-CDF path — the "
     "variant the capability matrix used to refuse (ISSUE 16); the stage "
     "table attributes the sample-stage share vs the XLA sampler-weighted "
     "row and recompiles_steady must stay 0"),
    ("sampler-weighted", "benchmarks.bench_sampler",
     ["--mode", "HBM", "--weighted", "--stream", "128", "--dedup", "both"],
     "weight-proportional draws — the path the reference never shipped "
     "reachable (quiver.cu.hpp:240-272)"),
    ("feature-replicate-xla", "benchmarks.bench_feature",
     ["--policy", "replicate", "--kernel", "xla", "--stream", "32"],
     "XLA-gather control for the kernel=auto row"),
    ("feature-bf16", "benchmarks.bench_feature",
     ["--policy", "replicate", "--dtype", "bf16", "--stream", "32"],
     "bf16 rows: 2x rows/s at equal GB/s, 2x cache rows per budget"),
    ("feature-int8", "benchmarks.bench_feature",
     ["--policy", "replicate", "--dtype", "int8", "--stream", "32"],
     "int8 quantized rows (absmax/row): ~4x cache rows per budget"),
    ("epoch-fused-bf16", "benchmarks.bench_epoch",
     ["--fused", "--bf16", "--cache-ratio", "1.0"],
     "fused + mixed precision: the framework's best-case per-step config"),
    ("epoch-hbm", "benchmarks.bench_epoch", ["--mode", "HBM"],
     "ref 11.1 s/epoch (1 GPU, Introduction_en.md:146-149)"),
    ("epoch-bf16", "benchmarks.bench_epoch", ["--mode", "HBM", "--bf16"],
     "mixed-precision (bf16 MXU matmuls + bf16 feature rows) vs the f32 row"),
    ("epoch-fused", "benchmarks.bench_epoch",
     ["--fused", "--cache-ratio", "1.0"],
     "ONE XLA program per step, full-HBM table — vs ref 11.1s AND its "
     "PyG-all-on-GPU 23.3s (Introduction_en.md:153-158)"),
    ("epoch-host", "benchmarks.bench_epoch", ["--mode", "HOST"],
     "beyond-HBM topology placement (unfused per-batch loop)"),
    ("epoch-scan-host", "benchmarks.bench_epoch",
     ["--scan-epoch", "--bf16", "--mode", "HOST", "--cache-ratio", "0.5"],
     "beyond-HBM FUSED: HOST topology + 50% cold tier through one "
     "compiled epoch program (r4; ref papers100M UVA path equivalent)"),
    ("sampler-stages", "benchmarks.bench_sampler",
     ["--mode", "HBM", "--stages", "--dedup", "both", "--iters", "8"],
     "per-layer sample/reindex stage attribution for the headline row"),
    ("rgcn", "benchmarks.bench_rgcn", ["--stream", "16"],
     "no reference baseline (hetero is beyond-parity)"),
    ("infer-layerwise", "benchmarks.bench_infer", [],
     "full-graph layer-wise inference (reference never benchmarked it)"),
    ("serve-latency", "benchmarks.bench_serve",
     ["--arrival", "closed", "--parity"],
     "online point-query serving: deadline-aware micro-batching over "
     "per-bucket AOT ladder programs (recompiles must stay 0 after "
     "warmup), p50/p95/p99 vs SLO + bitwise ladder==oracle parity; the "
     "reference's closest analogue is its IPC-shared Feature — it never "
     "shipped an end-to-end serving path"),
    ("serve-fleet", "benchmarks.bench_serve",
     ["--fleet", "2", "--parity"],
     "serving fleet scale-out over one persisted AOT-executable cache: "
     "replica joins deserialize instead of compiling (cold-start vs "
     "warm-join in the extras, steady recompiles asserted 0), gold/"
     "bronze SLO classes with per-class p99 and shed-before-gold "
     "admission; the reference's many-frontends-one-IPC-Feature pattern "
     "taken to whole-program replay"),
    ("feature-ooc", "benchmarks.ooc_drill", [],
     "out-of-core epoch under a HARD RLIMIT_AS budget: graph on disk at "
     ">= 4x the address-space headroom, pread-mode MmapFeatureStore + "
     "AsyncStager window readahead, 2-virtual-device CPU mesh in a "
     "subprocess (the limit is process-wide and irreversible); gates: "
     "epoch completes, readahead_hits > 0, recompiles_steady = 0 — the "
     "reference's closest analogue is mmap'd papers100M features over "
     "UVA, which it never bounded or measured"),
    ("saint-node", "benchmarks.bench_saint", ["--sampler", "node"],
     "no reference baseline (SAINT never landed there)"),
    ("validation", "benchmarks.tpu_validation", [],
     "compiled-Pallas validity + head-to-heads"),
    # last: single-chip mesh makes routed trivial on TPU; the 8-virtual-
    # device CPU floor (scripts/cpu_floor.sh) is the multi-device evidence
    ("feature-shard-routed", "benchmarks.bench_feature",
     ["--policy", "shard", "--routed", "--stream", "32"],
     "owner-routed all_to_all hot gather over the mesh feature axis "
     "(seed_sharding='all' trainer gather), dispatch-clean stream mode; "
     "UNCAPPED full-length buckets (F*L lanes/hop) — the capped row's "
     "comm-volume baseline"),
    ("feature-shard-routed-capped", "benchmarks.bench_feature",
     ["--policy", "shard", "--routed", "--routed-alpha", "2",
      "--stream", "32"],
     "capped-bucket routed gather: cap=ceil(2*L/F) per destination, "
     "~2*L lanes/hop vs the uncapped row's F*L (lanes_per_hop + measured "
     "overflow in the record; overflow lanes are fallback-served)"),
    ("feature-threetier", "benchmarks.bench_feature",
     ["--policy", "shard", "--routed", "--routed-alpha", "2",
      "--replicate-budget", "16M", "--stream", "32"],
     "three-tier store: top-degree rows replicated per chip (L0, zero "
     "interconnect lanes) in front of the capped routed sharded tier; "
     "per-tier hit rates + cap tightened by the measured L0 hit rate, "
     "effective lanes/hop = 2*L*(1-h0) vs the capped row's 2*L"),
    ("feature-controller", "benchmarks.bench_feature",
     ["--policy", "shard", "--routed", "--routed-alpha", "2",
      "--replicate-budget", "16M", "--controller"],
     "quiver-ctl replay: a recorded skewed trace (heat != degree) feeds "
     "the frequency sketch, repin re-tiers L0 to the measured-hot rows, "
     "and the record carries the measured L0 hit-rate delta vs the "
     "static degree-prefix placement at the SAME budget plus the "
     "audited JSONL decision-log path"),
    ("sampler-sharded", "benchmarks.bench_sampler",
     ["--mode", "HBM", "--topo-sharding", "mesh", "--routed-alpha", "2"],
     "mesh-sharded topology: CSR partitioned over the feature axis "
     "(~1/F topology bytes/chip, topo_shrink in the record), per-hop "
     "frontier routing over capped-bucket all_to_all — lanes-per-hop "
     "model + measured sample_overflow; bit-identical to the replicated "
     "sampler (tests/test_sharded_topology.py)"),
    ("sampler-hetero-sharded", "benchmarks.bench_rgcn",
     ["--topo-sharding", "mesh", "--routed-alpha", "2"],
     "hetero R-GCN epoch over per-relation mesh partitions "
     "(DistHeteroSampler): ONE shared route plan per (hop, dst type), "
     "per-edge-type lanes-per-hop model + per-(hop, edge type) "
     "sample_overflow; bit-identical to the replicated hetero sampler "
     "(tests/test_dist_hetero.py)"),
    ("memaudit", "benchmarks.memaudit", [],
     "graftmem gate: the mem rule family over the full program registry "
     "on the 2-device CPU audit mesh (trace-only, burns no chip time) + "
     "the per-target budget table; headline = tightest headroom "
     "fraction, fails on any finding or over-budget target"),
]

TIMEOUT = float(os.environ.get("QUIVER_BENCH_TIMEOUT", 1800))


def _harvest(stdout):
    recs = []
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                recs.append(rec)
    return recs


def _run_once(module, extra, env_overrides, timeout_s):
    env = dict(os.environ)
    env.update(env_overrides)
    env["QUIVER_BENCH_SUPERVISED"] = "1"
    env["PYTHONPATH"] = (
        REPO + os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else REPO
    )
    argv = [sys.executable, "-m", module] + extra
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout_s, env=env, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        out = e.stdout or ""
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        # a hung multi-record job (tpu_validation) may already have emitted
        # valid records — keep them
        return _harvest(out), f"timeout>{timeout_s:.0f}s"
    recs = _harvest(r.stdout)
    err = None
    if not recs:
        err = (r.stderr or r.stdout).strip()[-400:] or f"rc={r.returncode}"
    return recs, err


def run_job(module, extra, smoke, timeout_s):
    """Same discipline as the repo-root bench.py supervisor: children run
    with QUIVER_BENCH_SUPERVISED=1 (fail fast, no self-healing), so THIS
    function owns retry-on-error and the labeled CPU-smoke fallback."""
    extra = extra + (["--smoke"] if smoke else [])
    t0 = time.time()
    recs, err = _run_once(module, extra, {}, timeout_s)
    if not recs and not str(err).startswith("timeout"):
        print(f"[scoreboard] retrying once after: {str(err)[:120]}",
              file=sys.stderr, flush=True)
        time.sleep(15)
        recs, err = _run_once(module, extra, {}, timeout_s)
    if not recs:
        print("[scoreboard] falling back to labeled CPU smoke",
              file=sys.stderr, flush=True)
        fb = extra if "--smoke" in extra else extra + ["--smoke"]
        recs, fb_err = _run_once(
            module, fb,
            {"JAX_PLATFORMS": "cpu",
             "QUIVER_BENCH_DEGRADED": f"scoreboard fallback: {str(err)[:200]}"},
            min(timeout_s, 600),
        )
        if recs:
            err = None
        else:
            err = f"{err}; cpu fallback: {fb_err}"
    return recs, err, time.time() - t0


def _headline(rec):
    """Trajectory row for one benchmark record: the headline metric plus
    just enough provenance to compare rounds (full detail stays in
    tpu_results.json)."""
    row = {
        "metric": rec.get("metric"),
        "value": rec.get("value"),
        "unit": rec.get("unit", ""),
        "platform": rec.get("platform", "?"),
    }
    if rec.get("vs_baseline") is not None:
        row["vs_baseline"] = rec["vs_baseline"]
    if rec.get("degraded"):
        row["degraded"] = True
    if rec.get("smoke"):
        row["smoke"] = True
    return row


def _run_mode(rows):
    """``tpu`` when any row is an undegraded full-scale chip number,
    else ``cpu-smoke`` — the label the trajectory plots group by."""
    for row in rows.values():
        if (row.get("platform") == "tpu" and not row.get("degraded")
                and not row.get("smoke")):
            return "tpu"
    return "cpu-smoke"


def append_trajectory(entry, path=TRAJECTORY):
    """Append one consolidated per-round record to the trajectory ledger.

    Append-only on purpose: TPU_RESULTS.md and tpu_results.json are
    regenerated whole each round, so they only ever show the latest
    state; the ledger is the round-over-round history."""
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(entry, sort_keys=True) + "\n")


def trajectory_from_results(results, smoke, stamp):
    rows = {}
    for job in results:
        recs = job.get("records") or []
        if recs:
            # first record of a job is its headline (bench modules emit
            # the primary number first, attribution rows after)
            rows[job["key"]] = _headline(recs[0])
        else:
            rows[job["key"]] = {"error": (job.get("error") or "failed")[:200]}
    return {
        "when": stamp,
        "source": "scoreboard" + (" --smoke" if smoke else ""),
        "mode": _run_mode(rows),
        "rows": rows,
    }


def backfill_trajectory(path=TRAJECTORY):
    """Reconstruct the early rounds from the archived ``BENCH_r0*.json``
    supervisor captures and splice them in FRONT of any records already
    in the ledger (which are newer by construction). Prior backfilled
    round entries are replaced, not duplicated, so the command is
    idempotent; scoreboard-appended entries are preserved."""
    kept = []
    if os.path.exists(path):
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if d.get("source") != "bench.py":
                    kept.append(d)
    rounds = []
    for name in sorted(os.listdir(REPO)):
        if not (name.startswith("BENCH_r") and name.endswith(".json")):
            continue
        with open(os.path.join(REPO, name)) as fh:
            cap = json.load(fh)
        parsed = cap.get("parsed")
        if parsed:
            rows = {"sampler-hbm": _headline(parsed)}
        else:
            rows = {}
        entry = {
            "round": cap.get("n"),
            "source": "bench.py",
            "archive": name,
            "mode": _run_mode(rows),
            "rows": rows,
        }
        if not rows:
            entry["error"] = f"rc={cap.get('rc')}: no parsed record"
        rounds.append(entry)
    with open(path, "w", encoding="utf-8") as fh:
        for d in rounds + kept:
            fh.write(json.dumps(d, sort_keys=True) + "\n")
    return len(rounds), len(kept)


def fmt_value(rec):
    v, unit = rec.get("value"), rec.get("unit", "")
    if v is None:
        return "—"
    if unit == "SEPS":
        return f"{v / 1e6:.2f}M SEPS"
    return f"{v:g} {unit}"


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--only", nargs="*", default=None,
                   help="subset of job keys to run")
    p.add_argument("--out", default=os.path.join(REPO, "docs"))
    p.add_argument("--backfill-trajectory", action="store_true",
                   help="rebuild the early BENCH_TRAJECTORY.jsonl rounds "
                        "from the archived BENCH_r0*.json captures and exit")
    args = p.parse_args()

    if args.backfill_trajectory:
        n_rounds, n_kept = backfill_trajectory()
        print(f"[scoreboard] trajectory: {n_rounds} backfilled rounds + "
              f"{n_kept} kept entries -> {TRAJECTORY}", file=sys.stderr)
        return

    known = {key for key, *_ in JOBS}
    if args.only:
        unknown = set(args.only) - known
        if unknown:
            p.error(f"unknown job keys: {sorted(unknown)} "
                    f"(choose from {sorted(known)})")

    results = []
    for key, module, extra, note in JOBS:
        if args.only and key not in args.only:
            continue
        print(f"[scoreboard] {key}: {module} {' '.join(extra)}",
              file=sys.stderr, flush=True)
        recs, err, dt = run_job(module, extra, args.smoke, TIMEOUT)
        print(f"[scoreboard] {key}: {len(recs)} records in {dt:.0f}s"
              + (f" (error: {err[:120]})" if err else ""),
              file=sys.stderr, flush=True)
        results.append({"key": key, "note": note, "records": recs,
                        "error": err, "seconds": round(dt, 1)})

    write_outputs(results, args.out, args.smoke, merge=bool(args.only))


def write_outputs(results, out, smoke, merge=False, trajectory_path=None):
    """Write ``tpu_results.json`` + ``TPU_RESULTS.md`` from job results.

    ``merge=True`` folds ``results`` into the existing json (keyed by job)
    instead of replacing it — used by partial re-runs (``--only``) and by
    the single-process chip-window runner (scripts/mega_session.py), which
    writes after EVERY job so a mid-window kill loses nothing.

    ``trajectory_path`` overrides where the consolidated round record is
    appended (default: the repo-root ledger ``TRAJECTORY``). Tests MUST
    pass a scratch path (or monkeypatch ``TRAJECTORY``) — the default
    ledger is the authoritative round-over-round history and must only
    ever receive real runs.
    """
    os.makedirs(out, exist_ok=True)
    json_path = os.path.join(out, "tpu_results.json")
    if merge and os.path.exists(json_path):
        # partial re-run: merge into the existing scoreboard instead of
        # wiping rows that weren't in the subset
        try:
            with open(json_path) as fh:
                prior = {j["key"]: j for j in json.load(fh).get("jobs", [])}
        except (ValueError, KeyError):
            prior = {}
        def _quality(job):
            """Evidence rank of a job row: 2 full-scale TPU, 1 smoke/degraded
            TPU, 0 CPU/none. Higher-ranked prior rows must never be silently
            replaced by lower-ranked re-runs (a smoke rehearsal pointed at
            the same out dir would otherwise erase chip evidence)."""
            best = 0
            for rec in job.get("records") or []:
                if rec.get("platform") == "tpu" and not rec.get("stale"):
                    if rec.get("smoke") or rec.get("degraded"):
                        best = max(best, 1)
                    else:
                        best = max(best, 2)
            return best

        for job in results:
            old = prior.get(job["key"])
            if old and old.get("records") and not job.get("records"):
                # a failed re-run must not clobber earlier good evidence;
                # keep the good row, note the newer failure on it
                old = dict(old)
                old["retry_error"] = job.get("error")
                prior[job["key"]] = old
                continue
            if old and _quality(old) > _quality(job):
                # weaker evidence (smoke/degraded/CPU) must not displace a
                # full-scale TPU row; keep the strong row and stash the
                # newer weak one so nothing is lost either way
                old = dict(old)
                old["superseded_attempt"] = {
                    k: job.get(k)
                    for k in ("records", "error", "seconds", "smoke")
                }
                prior[job["key"]] = old
                continue
            prior[job["key"]] = job
        order = [key for key, *_ in JOBS]
        results = sorted(
            prior.values(),
            key=lambda j: order.index(j["key"]) if j["key"] in order else 99,
        )
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    with open(json_path, "w") as fh:
        json.dump({"when": stamp, "smoke": smoke, "jobs": results}, fh,
                  indent=1)

    lines = [
        "# TPU scoreboard",
        "",
        f"Generated by `python -m benchmarks.scoreboard` at {stamp}"
        + (" (SMOKE shapes)" if smoke else "") + ".",
        "",
        "| Job | Metric | Value | vs baseline | Platform | Reference point |",
        "|---|---|---|---|---|---|",
    ]
    for job in results:
        if not job["records"]:
            lines.append(
                f"| {job['key']} | — | FAILED | — | — | {job['note']} |"
            )
            continue
        for rec in job["records"]:
            vs = rec.get("vs_baseline")
            plat = rec.get("platform", "?")
            if rec.get("degraded"):
                plat += " (degraded)"
            if rec.get("smoke"):
                # per-record stamp so merged tables can mix full-scale and
                # smoke rows without the header mislabeling either
                plat += " (smoke)"
            if job.get("retry_error"):
                plat += " [kept: newer retry failed]"
            metric = rec.get("metric", "?")
            extras = {k: v for k, v in rec.items()
                      if k in ("kernel", "mode", "policy", "caps", "sampler",
                               "layer", "stage", "dispatch", "stream_batches",
                               "dedup", "roofline_frac", "ceiling_gbps",
                               "topo_mode", "cache_ratio", "elected",
                               "model", "prng", "hit_rep", "hit_cold",
                               "effective_lanes_per_hop", "topo_sharding",
                               "topo_shrink", "comm_reduction",
                               "overlap_efficiency", "scan_speedup",
                               "recompiles_steady", "pipeline_depth",
                               "prefetch", "store", "graph_over_budget",
                               "readahead_hits", "replicas", "p99_gold_ms",
                               "p99_bronze_ms", "shed_gold", "shed_bronze",
                               "cold_start_s", "warm_join_s")}
            if extras:
                metric += " " + ",".join(f"{k}={v}" for k, v in extras.items())
            lines.append(
                f"| {job['key']} | {metric} | {fmt_value(rec)} | "
                f"{vs if vs is not None else '—'} | {plat} | {job['note']} |"
            )
    lines += [
        "",
        "`vs baseline` > 1 always means better than the reference "
        "(value/baseline for throughput, baseline/value for times).",
        "",
    ]
    with open(os.path.join(out, "TPU_RESULTS.md"), "w") as fh:
        fh.write("\n".join(lines))
    append_trajectory(trajectory_from_results(results, smoke, stamp),
                      path=trajectory_path or TRAJECTORY)
    print("\n".join(lines))


if __name__ == "__main__":
    main()
