"""Shared plumbing for the benchmark harnesses.

Mirrors the reference's benchmark conventions (SURVEY §6): dataset-free
synthetic power-law graphs (benchmarks/generated_graph/gen_graph.py),
synchronized timing, and the canonical metrics — SEPS for sampling
(benchmarks/sample/bench_sampler.py:33-43), GB/s for feature collection
(benchmarks/feature/bench_feature.py:35-46), trimmed-mean iteration time for
end-to-end epochs (benchmarks/ogbn-papers100M/dist_sampling_ogb_paper100M_quiver.py:159-165).

Every script prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", ...extras}`` — the same schema
as the repo-root ``bench.py`` headline benchmark.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


# ogbn-products scale: 2.45M nodes, 123.7M edges (docs/Introduction_en.md)
PRODUCTS_NODES = 2_450_000
PRODUCTS_AVG_DEG = 50.5
PRODUCTS_TRAIN_NODES = 196_615


def base_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--nodes", type=int, default=PRODUCTS_NODES)
    p.add_argument("--avg-degree", type=float, default=PRODUCTS_AVG_DEG)
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    return p


def build_graph(args):
    """Synthetic products-scale power-law CSRTopo (+ build-time report)."""
    import os

    import jax

    # honor a JAX_PLATFORMS=cpu request via config (the image's sitecustomize
    # pins the TPU plugin before env vars are read; backend init is lazy so
    # this still takes effect — same workaround as tests/conftest.py)
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        jax.config.update("jax_platforms", "cpu")

    from quiver_tpu import CSRTopo
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    t0 = time.time()
    ei = generate_pareto_graph(args.nodes, args.avg_degree, seed=args.seed)
    topo = CSRTopo(edge_index=ei)
    del ei
    log(
        f"graph: {topo.node_count} nodes, {topo.edge_count} edges "
        f"({time.time()-t0:.1f}s build); device={jax.devices()[0]}"
    )
    return topo


def log(msg: str) -> None:
    print(msg, file=sys.stderr)


def emit(metric: str, value: float, unit: str, baseline: float | None, **extras):
    """Print the one-line JSON result. ``vs_baseline`` > 1 means better than
    the reference (for time metrics pass baseline/value via ``invert``)."""
    rec = {
        "metric": metric,
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": None if baseline is None else round(value / baseline, 3),
    }
    rec.update(extras)
    print(json.dumps(rec))
    return rec
