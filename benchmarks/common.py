"""Shared plumbing for the benchmark harnesses.

Mirrors the reference's benchmark conventions (SURVEY §6): dataset-free
synthetic power-law graphs (benchmarks/generated_graph/gen_graph.py),
synchronized timing, and the canonical metrics — SEPS for sampling
(benchmarks/sample/bench_sampler.py:33-43), GB/s for feature collection
(benchmarks/feature/bench_feature.py:35-46), trimmed-mean iteration time for
end-to-end epochs (benchmarks/ogbn-papers100M/dist_sampling_ogb_paper100M_quiver.py:159-165).

Every script prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", ...extras}`` — the same schema
as the repo-root ``bench.py`` headline benchmark.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


# ogbn-products scale: 2.45M nodes, 123.7M edges (docs/Introduction_en.md)
PRODUCTS_NODES = 2_450_000
PRODUCTS_AVG_DEG = 50.5
PRODUCTS_TRAIN_NODES = 196_615

# reference 1-GPU UVA SEPS on ogbn-products [15,10,5] (Introduction_en.md:41)
BASELINE_UVA_SEPS = 34.29e6


def stream_seps(sampler, node_count: int, batch: int, stream: int, rng,
                reps: int = 3):
    """Shared fused-stream SEPS measurement: ONE compiled program scans
    ``stream`` seed batches (in-program valid-edge tallies, one scalar
    readback). Used by bench_sampler's --stream headline and sweep_sampler.

    Returns (median SEPS, last overflow, stream actually used), or None
    when even a single batch's worst-case edge count would wrap the int32
    in-carry tally (no stream config is sound then — the caller's per-call
    number stands).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    run, caps = sampler._compiled(batch)
    ins = (batch,) + tuple(caps[:-1])
    max_epb = sum(i * k for i, k in zip(ins, sampler.sizes))
    if max_epb > 2**31 - 1:
        log(f"stream skipped: worst-case {max_epb} edges/batch exceeds the "
            "int32 tally range")
        return None
    max_stream = max(1, (2**31 - 1) // max(max_epb, 1))
    if stream > max_stream:
        log(f"stream clamped {stream} -> {max_stream} "
            f"(int32 edge-tally bound at <= {max_epb} edges/batch)")
        stream = max_stream
    n_vec = jnp.full((stream,), jnp.int32(batch))

    @jax.jit
    def streamf(topo_dev, seed_mat, nums, key0):
        def step(carry, xs):
            key, total, oflo = carry
            seeds, n = xs
            key, sub = jax.random.split(key)
            _, _, _, overflow, ec, _ = run(topo_dev, seeds, n, sub)
            return (key, total + jnp.sum(jnp.stack(ec)), oflo + overflow), None
        init = (key0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        (_, total, oflo), _ = lax.scan(step, init, (seed_mat, nums))
        return total, oflo

    import numpy as np

    def one_rep():
        seed_np = rng.integers(0, node_count, (stream, batch)).astype(np.int32)
        key = jax.random.PRNGKey(int(rng.integers(1 << 30)))
        t0 = time.time()
        total, oflo = streamf(sampler.topo, jnp.asarray(seed_np), n_vec, key)
        total, oflo = int(total), int(oflo)
        return total / (time.time() - t0), oflo

    t0 = time.time()
    one_rep()  # compile
    log(f"stream compile: {time.time()-t0:.1f}s ({stream} batches/scan)")
    results = [one_rep() for _ in range(reps)]
    seps = float(np.median([r[0] for r in results]))
    return seps, results[-1][1], stream


def hbm_bandwidth_gbps() -> float | None:
    """Nominal HBM bandwidth of the current device for roofline estimates.

    Env-overridable (QUIVER_HBM_GBPS). Defaults: TPU v5e ("v5 lite", the
    tunneled chip) 819 GB/s; unknown platforms return None and callers skip
    the roofline line rather than report one against a made-up ceiling.
    """
    import os

    env = os.environ.get("QUIVER_HBM_GBPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        d = jax.devices()[0]
        if d.platform == "tpu":
            # normalize "TPU v5 lite" / "tpu-v5e" spellings before matching
            kind = str(getattr(d, "device_kind", "")).lower()
            kind = kind.replace(" ", "").replace("-", "").replace("_", "")
            for tag, bw in (("v5lite", 819.0), ("v5e", 819.0),
                            ("v5p", 2765.0), ("v6", 1640.0), ("v4", 1228.0)):
                if tag in kind:
                    return bw
            # unrecognized TPU: no ceiling is better than a made-up one
    except Exception:  # noqa: BLE001
        pass
    return None


def sampler_roofline(sampler, batch: int, dedup: str):
    """Coarse HBM-traffic lower bound for ONE seed batch through the fused
    sampler — the denominator for "how far from the chip's ceiling is this
    SEPS number" (VERDICT r3 item 2), not a precise model.

    Traffic counted per layer (worst-case frontiers = the static caps):
    sample: 2 indptr gathers (base/deg) + the random CSR indices gather +
    the neighbor write; reindex: map dedup = map memset + random scatter +
    random gather + compacted write, sort dedup = ~log2(T) passes over
    (value, position) pairs. Every RANDOM 4-byte access is charged a full
    32-byte HBM granule — a pure-byte count would put the ceiling ~8x too
    high for gather-dominated programs. Returns (bytes_per_batch,
    ceiling_seps) or None when bandwidth is unknown.
    """
    import math

    bw = hbm_bandwidth_gbps()
    if bw is None:
        return None
    GRANULE = 32  # bytes served per random access
    _, caps = sampler._compiled(batch)
    ins = (batch,) + tuple(caps[:-1])
    ptr_b = max(sampler.topo.indptr.dtype.itemsize, GRANULE)
    n_bound = sampler.csr_topo.node_count
    total = 0
    worst_edges = 0
    for l, (S, k) in enumerate(zip(ins, sampler.sizes)):
        # base+deg are adjacent indptr slots: one granule per row; the k
        # CSR slots per row are contiguous strata picks — charge a granule
        # each (pessimistic for low-degree rows, right for high-degree)
        total += S * ptr_b + S * k * GRANULE + S * k * 4  # reads + write
        worst_edges += S * k
        T = S * k + S
        if dedup == "map":
            # sequential memset + random scatter + random gather + write
            total += n_bound * 4 + 2 * T * GRANULE + caps[l] * 4
        elif dedup == "scan":
            # two sorts + scans + a binary-search compaction: pure bytes
            # for the sorts, a granule per search probe
            total += 2 * int(math.log2(max(T, 2))) * T * 8
            total += int(math.log2(max(T, 2))) * caps[l] * GRANULE + caps[l] * 4
        else:
            # sort passes stream sequentially: pure bytes
            total += int(math.log2(max(T, 2))) * T * 8 + caps[l] * 4
    ceiling = worst_edges / (total / (bw * 1e9))
    return total, ceiling


def _enable_compilation_cache():
    """Persistent XLA compilation cache shared across bench processes.

    Every benchmark runs as its own supervised subprocess, and products-scale
    programs cost minutes of compile each — without a disk cache the
    scoreboard pays that per job per run. Platform is part of the cache key,
    so TPU and CPU-fallback runs never collide. Best-effort: an old jax
    without the API or an unwritable dir must not break a measurement run.
    """
    import os

    # forced-CPU runs (smokes, fallbacks) skip the cache: CPU executables
    # are cheap to compile, and cached ones carry machine-feature flags
    # that trip cross-host AOT loader warnings
    plats = [p.strip().lower()
             for p in os.environ.get("JAX_PLATFORMS", "").split(",")
             if p.strip()]
    if plats == ["cpu"]:
        return
    try:
        import jax

        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"),
        )
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:  # noqa: BLE001
        pass


def base_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--nodes", type=int, default=PRODUCTS_NODES)
    p.add_argument("--avg-degree", type=float, default=PRODUCTS_AVG_DEG)
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--smoke",
        action="store_true",
        help="tiny graph + few iters: a degraded environment still yields a number",
    )
    p.add_argument(
        "--backend-retries",
        type=int,
        default=1,
        help="extra attempts if the first backend touch fails (transient TPU grab)",
    )
    p.add_argument(
        "--backend-retry-delay",
        type=float,
        default=15.0,
        help="seconds between backend attempts",
    )
    return p


_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices()[0];"
    "jnp.zeros(8).block_until_ready();"
    "print(d.platform, flush=True)"
)


def _probe_subprocess(timeout_s: float):
    """Touch the backend in a THROWAWAY subprocess first.

    The TPU plugin can hang indefinitely during setup (observed: 10 minutes
    with no output) — an in-process jax.devices() hang is uninterruptible,
    so the watchdog must live outside the process. Returns (ok, detail).
    """
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung > {timeout_s:.0f}s (killed)"
    if r.returncode != 0:
        return False, (r.stderr or r.stdout).strip()[-500:]
    return True, r.stdout.strip()


def _init_inprocess(timeout_s: float):
    """In-process backend init under a watchdog thread.

    Even after a successful subprocess probe, another tenant can grab the
    TPU in the window before our own init — and that hang is indefinite.
    Returns (device | None, error | None). On timeout the daemon thread is
    abandoned (it may hold jax's backend lock — the caller must NOT retry
    backend init in this process; re-exec instead).
    """
    import threading

    import jax

    result = {}

    def target():
        try:
            result["dev"] = jax.devices()[0]
        except Exception as e:  # noqa: BLE001 — report any init failure
            result["err"] = str(e)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return None, f"in-process backend init hung > {timeout_s:.0f}s"
    if "err" in result:
        return None, result["err"]
    return result["dev"], None


def _reexec_cpu_smoke(reason: str):
    """Replace this (backend-poisoned) process with a CPU smoke run.

    After an in-process init hang, jax's backend lock may be held by the
    abandoned thread, so no further jax work is possible here. exec gives a
    clean interpreter; the degraded reason rides through the environment.
    """
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["QUIVER_BENCH_DEGRADED"] = reason[:300]
    # keep the repo root importable: `python -m benchmarks.X` re-execs by
    # script path (sys.argv[0]), which would otherwise put benchmarks/ on
    # sys.path instead of the root and break `from benchmarks.common import`
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else repo_root
    )
    spec = getattr(sys.modules.get("__main__"), "__spec__", None)
    if spec is not None and spec.name:
        argv = [sys.executable, "-m", spec.name] + sys.argv[1:]
    else:
        argv = [sys.executable] + sys.argv
    if "--smoke" not in argv:
        argv.append("--smoke")
    log(f"re-exec as CPU smoke run: {' '.join(argv[1:])}")
    os.execve(sys.executable, argv, env)


def _supervised() -> bool:
    """True when the repo-root ``bench.py`` supervisor is watchdogging us.

    Under the supervisor the division of labor changes: IT owns hang
    timeouts, retries, and the CPU fallback, so this process must (a) not
    burn budget on the throwaway subprocess probe — which also briefly holds
    the single chip right before our own init, the r02 contention suspect —
    and (b) fail FAST on errors instead of self-healing, so the supervisor
    can retry on the real backend before degrading.
    """
    import os

    return bool(os.environ.get("QUIVER_BENCH_SUPERVISED"))


def _select_prng(platform: str) -> str | None:
    """Pick the PRNG implementation for benchmark runs.

    Threefry (jax's default) burns vector cycles generating bits; XLA's
    ``rbg`` RngBitGenerator is the fast TPU path and the sampler draws
    ~1M randints per products batch, so on TPU benchmarks default to rbg
    (override with QUIVER_PRNG=threefry|rbg|default). Correctness is
    PRNG-agnostic — the validity oracle and dedup semantics never depend
    on WHICH uniform bits arrive (tests/test_sampler_api.py) — only
    draw-for-draw reproducibility across impls changes, which no recorded
    artifact relies on. Returns the impl applied, or None for default.
    """
    import os

    import jax

    forced = os.environ.get("QUIVER_PRNG", "").strip().lower()
    known = ("threefry", "threefry2x32", "rbg", "unsafe_rbg", "default")
    if forced and forced not in known:
        # the env var FORCES an impl during chip windows; a typo silently
        # measuring the default would be recorded as the forced impl —
        # same rule as resolve_platform_strategy
        raise ValueError(f"QUIVER_PRNG={forced!r} is not one of {known}")
    impl = forced or ("rbg" if platform == "tpu" else "")
    if impl in ("", "default", "threefry", "threefry2x32"):
        return None
    try:
        jax.config.update("jax_default_prng_impl", impl)
        return impl
    except Exception as e:  # noqa: BLE001 — an UNFORCED perf default must
        # not kill a run (e.g. a backend without the rbg impl)
        if forced:
            raise
        log(f"prng impl {impl!r} not applied: {e}")
        return None


def _finish_init(dev):
    """Post-init knobs applied on EVERY successful backend resolution."""
    impl = _select_prng(dev.platform)
    if impl:
        log(f"prng: {impl}")
        set_record_context(prng=impl)
    return dev


def init_backend(retries: int = 1, delay: float = 15.0, probe_timeout: float = 180.0):
    """Touch the JAX backend FIRST and fail fast with a diagnostic.

    Round-1 lesson: the harness spent minutes building a 123M-edge graph
    before the first `jax.devices()` call, then died inside a log f-string
    when the TPU plugin was unavailable — and the plugin can also HANG
    instead of erroring. So: (1) probe in a subprocess under a watchdog
    timeout, retrying for transient TPU-grab races; (2) initialize
    in-process under its own watchdog; (3) if nothing is usable, either
    exit nonzero (QUIVER_BENCH_STRICT) or fall back to a clearly-labeled
    CPU smoke run — always within minutes, never an unbounded hang.
    """
    import os

    import jax

    global _DEGRADED_REASON
    if os.environ.get("QUIVER_BENCH_DEGRADED"):
        # we are the re-exec'd CPU child of a failed accelerator run
        _DEGRADED_REASON = os.environ["QUIVER_BENCH_DEGRADED"]

    # honor an explicit CPU-only request via config (the image's
    # sitecustomize pins the TPU plugin before env vars are read; backend
    # init is lazy so this still takes effect — same workaround as
    # tests/conftest.py). Exact match only: a priority list like "tpu,cpu"
    # is NOT a forced-CPU request.
    plats = [
        p.strip().lower()
        for p in os.environ.get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    ]
    if plats == ["cpu"]:
        jax.config.update("jax_platforms", "cpu")
        # CPU backend cannot hang; skip the subprocess probe
        dev = jax.devices()[0]
        log(f"backend ok: {dev.platform} (forced cpu)")
        return _finish_init(dev)

    if _supervised():
        # no probe, no watchdog thread: the supervisor kills us on hang and
        # retries on error. Just touch the backend directly.
        dev = jax.devices()[0]
        log(f"backend ok: {dev.platform} (supervised)")
        return _finish_init(dev)

    last_err = None
    inproc_hung = False
    for attempt in range(retries + 1):
        t0 = time.time()
        ok, detail = _probe_subprocess(probe_timeout)
        if ok:
            log(f"backend probe ok: {detail} ({time.time() - t0:.1f}s)")
            dev, err = _init_inprocess(probe_timeout)
            if dev is not None:
                return _finish_init(dev)
            detail = err
            inproc_hung = "hung" in (err or "")
            if inproc_hung:
                last_err = detail
                break  # this process can't touch jax again; stop retrying
        last_err = detail
        log(f"backend init failed (attempt {attempt + 1}/{retries + 1}): {detail}")
        if attempt < retries:
            log(f"retrying in {delay:.0f}s...")
            time.sleep(delay)

    if os.environ.get("QUIVER_BENCH_STRICT"):
        log("FATAL: no usable JAX backend (QUIVER_BENCH_STRICT set; no fallback).")
        print(
            json.dumps(
                {
                    "metric": "backend-init",
                    "value": None,
                    "unit": "error",
                    "vs_baseline": None,
                    "error": str(last_err)[:500],
                }
            )
        )
        sys.exit(2)

    # degraded fallback: a clearly-labeled CPU number beats no number
    # (VERDICT r1 — the round must always produce a measurement)
    log(
        "WARNING: accelerator backend unusable; falling back to CPU smoke "
        "mode. The emitted number is NOT a TPU result. "
        f"(reason: {str(last_err)[:200]})"
    )
    if inproc_hung:
        _reexec_cpu_smoke(str(last_err))  # never returns
    jax.config.update("jax_platforms", "cpu")
    _DEGRADED_REASON = str(last_err)[:300]
    return _finish_init(jax.devices()[0])


# set when init_backend fell back to CPU; emit() stamps it into the JSON
_DEGRADED_REASON: str | None = None

# workload-identity fields (nodes, smoke) stamped into every emit() record
# so the TPU ledger can tell headline-scale measurements from smoke runs
_RECORD_CONTEXT: dict = {}


def set_record_context(**fields) -> None:
    """Merge workload-identity fields into all subsequent emit() records.

    ``None`` values are dropped (so ``smoke=None`` leaves clean records
    unannotated). Called by build_graph; harnesses with custom setup call it
    directly."""
    _RECORD_CONTEXT.update({k: v for k, v in fields.items() if v is not None})


def run_guarded(body, args):
    """Run the measured body (setup + first compile + measure) under the same
    failure discipline ``init_backend`` has.

    Round-2 lesson (VERDICT r2): the harness guarded backend *init* and then
    died, unguarded, at the first jit *compile*
    (``JaxRuntimeError: UNAVAILABLE``) — no JSON, rc=1. Every benchmark's
    post-argparse work goes through here:

    * on exception, retry once after a delay (the observed failure pattern —
      probe ok, first compile UNAVAILABLE — is transient single-chip
      contention; a fresh attempt recompiles from scratch);
    * supervised (repo-root ``bench.py``): exhausted retries exit nonzero
      fast so the supervisor can retry on the real backend before degrading;
    * standalone strict (``QUIVER_BENCH_STRICT``): emit an error-labeled JSON
      line and exit 2;
    * standalone default: re-exec as a CPU smoke run — a labeled degraded
      number beats no number.
    """
    import os

    retries = getattr(args, "backend_retries", 1)
    delay = getattr(args, "backend_retry_delay", 15.0)
    _enable_compilation_cache()  # backend plumbing: after argparse, before jax work
    last = None
    for attempt in range(retries + 1):
        try:
            return body()
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — any failure must yield JSON
            last = f"{type(e).__name__}: {str(e)[:400]}"
            log(f"measured body failed (attempt {attempt + 1}/{retries + 1}): {last}")
            if attempt < retries:
                log(f"retrying in {delay:.0f}s...")
                time.sleep(delay)

    if _supervised():
        log("FATAL: measured body failed after retries (supervised; "
            "supervisor owns the fallback).")
        sys.exit(3)
    if os.environ.get("QUIVER_BENCH_STRICT"):
        print(json.dumps({
            "metric": "measured-body",
            "value": None,
            "unit": "error",
            "vs_baseline": None,
            "error": last,
        }), flush=True)
        sys.exit(2)
    log("WARNING: measured body unrunnable on this backend; re-exec as CPU "
        f"smoke. (reason: {last})")
    _reexec_cpu_smoke(last)  # never returns


def apply_smoke(args) -> None:
    """Shrink the workload so a degraded environment still finishes fast."""
    if getattr(args, "smoke", False):
        args.nodes = min(args.nodes, 200_000)
        args.iters = min(args.iters, 5)
        args.warmup = min(args.warmup, 2)
        if getattr(args, "stream", 0):
            args.stream = min(args.stream, 4)
        if hasattr(args, "train_nodes"):
            args.train_nodes = min(args.train_nodes, 20_000)
        log(f"smoke mode: nodes={args.nodes} iters={args.iters}")


def _graphgen_tag() -> str:
    """Short content hash of the generator source.

    The cache key must change whenever generate_pareto_graph's output
    could: a (nodes, degree, seed)-only key silently serves stale graphs
    across generator edits — the same staleness class the explicit eid
    guard below already caught once.
    """
    import hashlib
    import os

    src = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "quiver_tpu", "utils", "graphgen.py",
    )
    try:
        with open(src, "rb") as fh:
            return hashlib.md5(fh.read()).hexdigest()[:8]
    except OSError:
        return "nosrc"


def _graph_cache_path(nodes: int, avg_degree: float, seed: int) -> str:
    import os

    d = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".graph_cache",
    )
    return os.path.join(
        d, f"pareto_n{nodes}_d{avg_degree:g}_s{seed}_g{_graphgen_tag()}.npz"
    )


def build_graph(args):
    """Synthetic products-scale power-law CSRTopo (+ build-time report).

    Touches the backend BEFORE the (potentially multi-minute) graph build so
    backend failures surface in seconds. The built CSR is cached on disk
    keyed by (nodes, avg_degree, seed): during a chip window the grant is
    held for the whole process lifetime, so every minute spent re-generating
    the same synthetic graph is a minute of hardware not measuring.
    """
    import os

    init_backend(
        retries=getattr(args, "backend_retries", 1),
        delay=getattr(args, "backend_retry_delay", 15.0),
    )
    if _DEGRADED_REASON is not None:
        args.smoke = True  # degraded CPU fallback: shrink to smoke scale
    apply_smoke(args)

    from quiver_tpu import CSRTopo

    t0 = time.time()
    cache = _graph_cache_path(args.nodes, args.avg_degree, args.seed)
    topo = None
    if os.path.exists(cache):
        try:
            import numpy as np

            z = np.load(cache)
            if "eid" not in z.files:
                # pre-eid-fix cache: both CSR builders always produce eid,
                # so its absence means a stale file — regenerate, don't
                # silently load an inequivalent topology
                raise ValueError("stale cache (no eid)")
            topo = CSRTopo(indptr=z["indptr"], indices=z["indices"],
                           eid=z["eid"])
            log(f"graph: loaded CSR cache {os.path.basename(cache)}")
        except Exception as e:  # noqa: BLE001 — cache must never break a run
            log(f"graph cache load failed ({e}); regenerating")
            topo = None
    if topo is None:
        from quiver_tpu.utils.graphgen import generate_pareto_graph

        ei = generate_pareto_graph(args.nodes, args.avg_degree, seed=args.seed)
        topo = CSRTopo(edge_index=ei)
        del ei
        try:
            import numpy as np

            os.makedirs(os.path.dirname(cache), exist_ok=True)
            tmp = cache + ".tmp"
            arrays = {"indptr": topo.indptr, "indices": topo.indices}
            if topo.eid is not None:
                # equivalence: a cache hit must carry the same eid the
                # COO build produced (with_eid consumers, HBM footprint)
                arrays["eid"] = topo.eid
            with open(tmp, "wb") as fh:
                np.savez(fh, **arrays)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, cache)
        except Exception as e:  # noqa: BLE001
            log(f"graph cache save failed ({e}); continuing uncached")
    log(
        f"graph: {topo.node_count} nodes, {topo.edge_count} edges "
        f"({time.time() - t0:.1f}s build)"
    )
    set_record_context(
        nodes=int(topo.node_count),
        smoke=True if getattr(args, "smoke", False) else None,
    )
    return topo


def model_from_name(name: str, hidden: int, classes: int,
                    num_layers: int, heads: int = 4, dtype=None):
    """Shared --model dispatch for the homogeneous families.

    Returns (model, layerwise_inference_fn, edge_sweeps_per_layer) — the
    sweep count feeds honest edge-throughput extras (GAT walks the edge
    array twice per layer: segment-max then the fused num/denom pass).
    """
    from quiver_tpu.models import (
        gat_layerwise_inference,
        gcn_layerwise_inference,
        gin_layerwise_inference,
        sage_layerwise_inference,
    )

    kw = dict(hidden=hidden, num_classes=classes, num_layers=num_layers,
              dtype=dtype)
    if name == "gat":
        from quiver_tpu.models.gat import GAT

        return GAT(**kw, heads=heads), gat_layerwise_inference, 2
    if name == "gcn":
        from quiver_tpu.models.gcn import GCN

        return GCN(**kw), gcn_layerwise_inference, 1
    if name == "gin":
        from quiver_tpu.models.gin import GIN

        return GIN(**kw), gin_layerwise_inference, 1
    if name == "sage":
        from quiver_tpu.models.sage import GraphSAGE

        return GraphSAGE(**kw), sage_layerwise_inference, 1
    raise ValueError(f"unknown model family {name!r}")


def trimmed_mean(times) -> float:
    """10%-trimmed mean of iteration times (the reference drops the first
    epoch and averages the rest; per-iteration trimming is the same idea at
    iter scale)."""
    import numpy as np

    times = np.sort(np.asarray(times, dtype=float))
    k = max(1, len(times) // 10)
    if len(times) > 2 * k:
        times = times[k:-k]
    return float(np.mean(times))


def log(msg: str) -> None:
    print(msg, file=sys.stderr)


def write_metrics(*sources, **extra) -> int:
    """Persist graftscope registry snapshots to the run's metrics.jsonl
    artifact (``QUIVER_METRICS_JSONL``; mega_session points it at its
    output dir — unset, the call is a no-op).

    ``sources``: objects carrying a ``.metrics`` registry (stores,
    samplers, trainers), bare registries, or ``None`` (skipped). Record-
    context fields (nodes, smoke, prng) and ``extra`` ride on every row so
    the artifact lines are attributable to their workload. Best-effort —
    telemetry persistence must never break a measurement run.
    """
    snaps = []
    for src in sources:
        if src is None:
            continue
        reg = getattr(src, "metrics", src)
        get = getattr(reg, "snapshots", None)
        if callable(get):
            snaps.extend(get())
    if not snaps:
        return 0
    fields = {k: v for k, v in _RECORD_CONTEXT.items()}
    fields.update({k: v for k, v in extra.items() if v is not None})
    try:
        from benchmarks import ledger

        n = ledger.append_metrics(snaps, extra=fields)
        if n:
            log(f"metrics: {n} snapshot rows -> {ledger.metrics_jsonl_path()}")
        return n
    except Exception as e:  # noqa: BLE001 — artifact write must not cost a run
        log(f"metrics artifact write failed: {type(e).__name__}: {e}")
        return 0


def emit(
    metric: str,
    value: float,
    unit: str,
    baseline: float | None,
    invert: bool = False,
    **extras,
):
    """Print the one-line JSON result. ``vs_baseline`` > 1 always means
    better than the reference: value/baseline for throughput metrics,
    baseline/value when ``invert=True`` (time/latency metrics where lower is
    better)."""
    if baseline is None:
        vs = None
    elif invert:
        vs = round(baseline / value, 3) if value else None
    else:
        vs = round(value / baseline, 3)
    rec = {
        "metric": metric,
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": vs,
    }
    try:
        import jax

        rec["platform"] = jax.devices()[0].platform
    except Exception:
        pass
    if _DEGRADED_REASON is not None:
        rec["degraded"] = _DEGRADED_REASON
    rec.update(_RECORD_CONTEXT)
    rec.update(extras)
    # flush: a supervisor timeout-kill must not discard records
    # sitting in the pipe's block buffer (r3 scoreboard lesson)
    print(json.dumps(rec), flush=True)
    # durable evidence: successful TPU records are persisted HERE, inside
    # the measured process, so a later timeout-kill or dead tunnel cannot
    # erase them (r3 lesson — the 9.70M headline survived only as markdown)
    try:
        from benchmarks import ledger

        if ledger.append(rec):
            log(f"ledger: appended {metric} to {ledger.path()}")
    except Exception:  # noqa: BLE001 — evidence persistence must not break a run
        pass
    return rec
