"""Online serving latency/throughput benchmark (quiver-serve).

Drives :class:`quiver_tpu.serving.InferenceServer` — the deadline-aware
micro-batch path over the resident sampler + tiered feature store — in
two arrival modes:

* ``--arrival closed`` (default): a closed loop keeps the top ladder
  bucket full — the max-throughput operating point (queries/sec/chip).
* ``--arrival open``: fixed-rate arrivals (``--rate`` qps) through the
  real clock — the latency-under-load operating point where the deadline
  coalescer actually earns its keep.

Metric: queries/sec/chip, with per-request p50/p95/p99 latency and the
p99-vs-SLO verdict in the extras, plus ``recompiles_steady`` (must be 0:
after warmup the ladder only replays compiled programs). ``--parity``
additionally asserts a sample of ladder responses bitwise against the
direct single-query oracle — the CI serve-smoke gate. No reference
baseline exists (the reference never served online); this row tracks the
framework's own capability.

``--fleet N`` switches to the open-loop FLEET lane (the ``serve-fleet``
row): N :class:`~quiver_tpu.serving.ServingFleet` replicas share one
feature store and one persisted AOT-executable cache (``--aot-cache``),
traffic is a gold/bronze SLO-class mix (``--gold-frac``) with per-class
deadlines, and the row reports per-class p99 vs per-class SLO, shed
counts, cold-start-to-first-response (cache cold vs warm joins), and
``recompiles_steady`` asserted 0. ``--expect-warm`` additionally asserts
the FIRST replica warmed entirely from the cache (zero compiles) — the
fresh-process restart gate CI's fleet-smoke job drives.
"""

import time

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded


def main():
    p = base_parser(__doc__)
    p.add_argument("--feature-dim", type=int, default=100)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=47)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--fanout", type=int, default=5)
    p.add_argument("--max-batch", type=int, default=8,
                   help="top of the power-of-two ladder")
    p.add_argument("--requests", type=int, default=512,
                   help="measured point queries (after warmup)")
    p.add_argument("--arrival", default="closed", choices=["closed", "open"])
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop arrival rate (queries/sec)")
    p.add_argument("--deadline-ms", type=float, default=50.0,
                   help="per-request deadline budget")
    p.add_argument("--slo-ms", type=float, default=100.0,
                   help="p99 latency SLO the row reports against")
    p.add_argument("--parity", action="store_true",
                   help="assert a sample of responses bitwise against the "
                   "direct single-query oracle (CI smoke gate)")
    p.add_argument("--fleet", type=int, default=0,
                   help="run the open-loop fleet lane with this many "
                   "replicas sharing one AOT cache (0 = single server)")
    p.add_argument("--aot-cache", default=None,
                   help="persisted AOT-executable cache directory shared "
                   "by the fleet (default: a fresh temp dir = cache-cold)")
    p.add_argument("--expect-warm", action="store_true",
                   help="assert the first replica warms from the cache "
                   "with ZERO compiles (the fresh-process restart gate)")
    p.add_argument("--gold-frac", type=float, default=0.7,
                   help="fraction of fleet-lane traffic in the gold class")
    p.add_argument("--bronze-deadline-ms", type=float, default=None,
                   help="bronze-class deadline (default 2x --deadline-ms)")
    p.add_argument("--bronze-slo-ms", type=float, default=None,
                   help="bronze-class p99 SLO (default 2x --slo-ms)")
    p.set_defaults(iters=1, warmup=1)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _closed_loop(server, nodes, top):
    """Keep the top bucket full; drain with forced flushes."""
    done = []
    for i in range(0, len(nodes), top):
        for n in nodes[i:i + top]:
            server.submit(int(n))
        while server.batcher.depth:
            done += server.pump(force=True)
    return done


def _open_loop(server, nodes, rate):
    """Fixed-rate arrivals on the real clock; the deadline coalescer
    decides the flushes."""
    done = []
    t0 = time.monotonic()
    gap = 1.0 / rate
    for i, n in enumerate(nodes):
        due = t0 + i * gap
        while True:
            now = time.monotonic()
            if now >= due:
                break
            if server.batcher.ready():
                done += server.pump()
            else:
                time.sleep(min(due - now, gap / 4))
        server.submit(int(n))
    while server.batcher.depth:
        done += server.pump(force=server.batcher.depth < server.batcher.buckets[-1])
    return done


def _build_stack(args):
    """The shared serving stack (graph, store, sampler, model, params)."""
    import numpy as np

    import jax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.train import empty_adjs, init_model

    topo = build_graph(args)
    n = topo.node_count
    if getattr(args, "smoke", False):
        args.requests = min(args.requests, 64)
    rng = np.random.default_rng(args.seed)
    x_all = rng.normal(size=(n, args.feature_dim)).astype(np.float32)
    feat = Feature(device_cache_size="8G").from_cpu_tensor(x_all)
    sampler = GraphSageSampler(topo, [args.fanout] * args.layers,
                               seed=args.seed)
    model = GraphSAGE(hidden=args.hidden, num_classes=args.classes,
                      num_layers=args.layers)
    adjs = empty_adjs([args.fanout] * args.layers, batch=8, node_count=n)
    params = init_model(
        model, jax.random.PRNGKey(args.seed),
        np.zeros((adjs[0].size[0], args.feature_dim), np.float32), adjs,
    )
    return n, rng, feat, sampler, model, params


def _body(args):
    import numpy as np

    import jax

    from quiver_tpu.serving import InferenceServer

    if args.fleet > 0:
        return _fleet_body(args)
    n, rng, feat, sampler, model, params = _build_stack(args)

    server = InferenceServer(
        sampler, model, params, feat, max_batch=args.max_batch,
        default_deadline_s=args.deadline_ms / 1e3, seed=args.seed,
    )
    t0 = time.time()
    compiles = server.warmup()
    log(f"warmup: {compiles} ladder programs compiled in "
        f"{time.time() - t0:.1f}s (buckets {server.batcher.buckets})")
    # a throwaway round flushes first-touch costs (gather-path tracing,
    # executable first replay) out of the measured window
    warm_nodes = rng.integers(0, n, args.max_batch)
    _closed_loop(server, warm_nodes, args.max_batch)
    recompiles_warm = server.recompiles
    misses_warm = server.stats()["deadline_misses"]

    nodes = rng.integers(0, n, args.requests)
    t0 = time.time()
    if args.arrival == "closed":
        done = _closed_loop(server, nodes, args.max_batch)
    else:
        done = _open_loop(server, nodes, args.rate)
    wall = time.time() - t0
    assert len(done) == args.requests, (len(done), args.requests)

    recompiles_steady = server.recompiles - recompiles_warm
    lat_ms = np.array([r.latency_s() * 1e3 for r in done])
    p50, p95, p99 = (float(np.percentile(lat_ms, q)) for q in (50, 95, 99))
    qps = args.requests / wall
    chips = jax.device_count()
    misses = server.stats()["deadline_misses"] - misses_warm

    parity = None
    if args.parity:
        checked = 0
        for r in done[:: max(1, len(done) // 16)]:
            oracle = server.oracle(r.node, r.seq)
            if not np.array_equal(r.result, oracle):
                raise AssertionError(
                    f"parity violation: node {r.node} seq {r.seq} ladder "
                    f"response != direct oracle"
                )
            checked += 1
        parity = f"ok:{checked}"
        log(f"parity: {checked} responses bitwise equal to the oracle")
    if recompiles_steady:
        raise AssertionError(
            f"steady-state recompiles: {recompiles_steady} (ladder must "
            f"only replay after warmup)"
        )

    log(server.timeline.report())
    emit(
        "serve-latency",
        qps / chips,
        "qps/chip",
        None,
        arrival=args.arrival,
        max_batch=args.max_batch,
        p50_ms=round(p50, 3),
        p95_ms=round(p95, 3),
        p99_ms=round(p99, 3),
        slo_ms=args.slo_ms,
        p99_within_slo=bool(p99 <= args.slo_ms),
        deadline_miss_rate=round(misses / args.requests, 4),
        recompiles_steady=recompiles_steady,
        requests=args.requests,
        **({"parity": parity} if parity else {}),
        **({"rate_qps": args.rate} if args.arrival == "open" else {}),
    )


def _fleet_open_loop(fleet, nodes, priorities, rate):
    """Fixed-rate arrivals routed across the fleet on the real clock;
    each replica's deadline coalescer decides its own flushes. Returns
    the admitted request handles (shed ones included — the caller
    attributes them per class)."""
    from quiver_tpu.serving import ServeQueueFull

    reqs = []
    t0 = time.monotonic()
    gap = 1.0 / rate
    for i, (n, pri) in enumerate(zip(nodes, priorities)):
        due = t0 + i * gap
        while True:
            now = time.monotonic()
            if now >= due:
                break
            if any(s.batcher.ready() for s in fleet.servers):
                fleet.pump()
            else:
                time.sleep(min(due - now, gap / 4))
        try:
            reqs.append(fleet.submit(int(n), priority=pri))
        except ServeQueueFull:
            pass  # hard rejection (counted in shed_by_class already)
    while any(s.batcher.depth for s in fleet.servers):
        fleet.pump(force=True)
    return reqs


def _fleet_body(args):
    import tempfile

    import numpy as np

    import jax

    from quiver_tpu.serving import PRIORITIES, ServingFleet

    n, rng, feat, sampler, model, params = _build_stack(args)
    cache_dir = args.aot_cache or tempfile.mkdtemp(prefix="quiver-aot-")
    gold_dl = args.deadline_ms / 1e3
    bronze_dl = (args.bronze_deadline_ms / 1e3 if args.bronze_deadline_ms
                 else 2 * gold_dl)
    slo = {"gold": args.slo_ms,
           "bronze": args.bronze_slo_ms or 2 * args.slo_ms}

    # -- cold start to first response (cache state decides cold vs warm) --
    t0 = time.perf_counter()
    fleet = ServingFleet(
        sampler, model, params, feat, replicas=1, aot_cache=cache_dir,
        seed=args.seed, max_batch=args.max_batch,
        class_deadlines={"gold": gold_dl, "bronze": bronze_dl},
    )
    fleet.serve(rng.integers(0, n, 1))
    first_response_s = time.perf_counter() - t0
    first = fleet.cold_starts[0]
    log(f"replica 0: first response {first_response_s:.2f}s "
        f"(loaded {first['loaded']}, compiled {first['compiled']} from "
        f"{cache_dir})")
    if args.expect_warm and (first["compiled"] or fleet.recompiles):
        raise AssertionError(
            f"--expect-warm: replica 0 compiled {first['compiled']} "
            f"programs (recompiles={fleet.recompiles}) instead of warming "
            f"from {cache_dir}"
        )

    # -- scale-out: every further replica must join compile-free --------------
    for _ in range(args.fleet - 1):
        fleet.add_replica()
    joins = fleet.cold_starts[1:]
    for j in joins:
        if j["compiled"]:
            raise AssertionError(
                f"replica join compiled {j['compiled']} programs against a "
                f"populated cache: {joins}"
            )
    warm_join_s = (float(np.mean([j["seconds"] for j in joins]))
                   if joins else None)
    recompiles_warm = fleet.recompiles

    # -- open-loop mixed-class traffic ---------------------------------------
    nodes = rng.integers(0, n, args.requests)
    priorities = np.where(rng.random(args.requests) < args.gold_frac,
                          "gold", "bronze")
    t0 = time.time()
    reqs = _fleet_open_loop(fleet, nodes, priorities, args.rate)
    wall = time.time() - t0

    stats = fleet.stats()
    recompiles_steady = fleet.recompiles - recompiles_warm
    if recompiles_steady:
        raise AssertionError(
            f"steady-state recompiles: {recompiles_steady} (a warm fleet "
            f"must only replay executables)"
        )
    served = [r for r in reqs if not r.shed]
    per_class = {}
    for cls in PRIORITIES:
        lat = np.array([r.latency_s() * 1e3 for r in served
                        if r.priority == cls])
        if lat.size == 0:
            per_class[cls] = {"p50": None, "p99": None}
            continue
        per_class[cls] = {
            "p50": float(np.percentile(lat, 50)),
            "p99": float(np.percentile(lat, 99)),
        }
        log(f"{cls}: {len(lat)} served, p99 {per_class[cls]['p99']:.2f}ms "
            f"(SLO {slo[cls]}ms), shed {stats['shed'][cls]}, "
            f"misses {stats['class_deadline_misses'][cls]}")

    parity = None
    if args.parity:
        checked = 0
        for r in served[:: max(1, len(served) // 16)]:
            oracle = fleet.oracle(r.node, r.seq)
            if not np.array_equal(r.result, oracle):
                raise AssertionError(
                    f"fleet parity violation: node {r.node} seq {r.seq}"
                )
            checked += 1
        parity = f"ok:{checked}"
        log(f"parity: {checked} fleet responses bitwise equal to the oracle")

    qps = len(served) / wall
    chips = jax.device_count()
    p99g, p99b = per_class["gold"]["p99"], per_class["bronze"]["p99"]
    emit(
        "serve-fleet",
        qps / chips,
        "qps/chip",
        None,
        replicas=args.fleet,
        rate_qps=args.rate,
        gold_frac=args.gold_frac,
        p99_gold_ms=round(p99g, 3) if p99g is not None else None,
        p99_bronze_ms=round(p99b, 3) if p99b is not None else None,
        gold_slo_ms=slo["gold"],
        bronze_slo_ms=slo["bronze"],
        p99_gold_within_slo=(None if p99g is None
                             else bool(p99g <= slo["gold"])),
        p99_bronze_within_slo=(None if p99b is None
                               else bool(p99b <= slo["bronze"])),
        shed_gold=stats["shed"]["gold"],
        shed_bronze=stats["shed"]["bronze"],
        cold_start_s=round(first_response_s, 3),
        cold_start_compiled=first["compiled"],
        cold_start_loaded=first["loaded"],
        warm_join_s=round(warm_join_s, 3) if warm_join_s else None,
        recompiles_steady=recompiles_steady,
        aot_cache_entries=stats["aot_cache"]["entries"],
        requests=args.requests,
        **({"parity": parity} if parity else {}),
    )


if __name__ == "__main__":
    main()
