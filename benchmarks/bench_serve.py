"""Online serving latency/throughput benchmark (quiver-serve).

Drives :class:`quiver_tpu.serving.InferenceServer` — the deadline-aware
micro-batch path over the resident sampler + tiered feature store — in
two arrival modes:

* ``--arrival closed`` (default): a closed loop keeps the top ladder
  bucket full — the max-throughput operating point (queries/sec/chip).
* ``--arrival open``: fixed-rate arrivals (``--rate`` qps) through the
  real clock — the latency-under-load operating point where the deadline
  coalescer actually earns its keep.

Metric: queries/sec/chip, with per-request p50/p95/p99 latency and the
p99-vs-SLO verdict in the extras, plus ``recompiles_steady`` (must be 0:
after warmup the ladder only replays compiled programs). ``--parity``
additionally asserts a sample of ladder responses bitwise against the
direct single-query oracle — the CI serve-smoke gate. No reference
baseline exists (the reference never served online); this row tracks the
framework's own capability.
"""

import time

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded


def main():
    p = base_parser(__doc__)
    p.add_argument("--feature-dim", type=int, default=100)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=47)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--fanout", type=int, default=5)
    p.add_argument("--max-batch", type=int, default=8,
                   help="top of the power-of-two ladder")
    p.add_argument("--requests", type=int, default=512,
                   help="measured point queries (after warmup)")
    p.add_argument("--arrival", default="closed", choices=["closed", "open"])
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop arrival rate (queries/sec)")
    p.add_argument("--deadline-ms", type=float, default=50.0,
                   help="per-request deadline budget")
    p.add_argument("--slo-ms", type=float, default=100.0,
                   help="p99 latency SLO the row reports against")
    p.add_argument("--parity", action="store_true",
                   help="assert a sample of responses bitwise against the "
                   "direct single-query oracle (CI smoke gate)")
    p.set_defaults(iters=1, warmup=1)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _closed_loop(server, nodes, top):
    """Keep the top bucket full; drain with forced flushes."""
    done = []
    for i in range(0, len(nodes), top):
        for n in nodes[i:i + top]:
            server.submit(int(n))
        while server.batcher.depth:
            done += server.pump(force=True)
    return done


def _open_loop(server, nodes, rate):
    """Fixed-rate arrivals on the real clock; the deadline coalescer
    decides the flushes."""
    done = []
    t0 = time.monotonic()
    gap = 1.0 / rate
    for i, n in enumerate(nodes):
        due = t0 + i * gap
        while True:
            now = time.monotonic()
            if now >= due:
                break
            if server.batcher.ready():
                done += server.pump()
            else:
                time.sleep(min(due - now, gap / 4))
        server.submit(int(n))
    while server.batcher.depth:
        done += server.pump(force=server.batcher.depth < server.batcher.buckets[-1])
    return done


def _body(args):
    import numpy as np

    import jax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.train import empty_adjs, init_model
    from quiver_tpu.serving import InferenceServer

    topo = build_graph(args)
    n = topo.node_count
    if getattr(args, "smoke", False):
        args.requests = min(args.requests, 64)
    rng = np.random.default_rng(args.seed)
    x_all = rng.normal(size=(n, args.feature_dim)).astype(np.float32)
    feat = Feature(device_cache_size="8G").from_cpu_tensor(x_all)
    sampler = GraphSageSampler(topo, [args.fanout] * args.layers,
                               seed=args.seed)
    model = GraphSAGE(hidden=args.hidden, num_classes=args.classes,
                      num_layers=args.layers)
    adjs = empty_adjs([args.fanout] * args.layers, batch=8, node_count=n)
    params = init_model(
        model, jax.random.PRNGKey(args.seed),
        np.zeros((adjs[0].size[0], args.feature_dim), np.float32), adjs,
    )

    server = InferenceServer(
        sampler, model, params, feat, max_batch=args.max_batch,
        default_deadline_s=args.deadline_ms / 1e3, seed=args.seed,
    )
    t0 = time.time()
    compiles = server.warmup()
    log(f"warmup: {compiles} ladder programs compiled in "
        f"{time.time() - t0:.1f}s (buckets {server.batcher.buckets})")
    # a throwaway round flushes first-touch costs (gather-path tracing,
    # executable first replay) out of the measured window
    warm_nodes = rng.integers(0, n, args.max_batch)
    _closed_loop(server, warm_nodes, args.max_batch)
    recompiles_warm = server.recompiles
    misses_warm = server.stats()["deadline_misses"]

    nodes = rng.integers(0, n, args.requests)
    t0 = time.time()
    if args.arrival == "closed":
        done = _closed_loop(server, nodes, args.max_batch)
    else:
        done = _open_loop(server, nodes, args.rate)
    wall = time.time() - t0
    assert len(done) == args.requests, (len(done), args.requests)

    recompiles_steady = server.recompiles - recompiles_warm
    lat_ms = np.array([r.latency_s() * 1e3 for r in done])
    p50, p95, p99 = (float(np.percentile(lat_ms, q)) for q in (50, 95, 99))
    qps = args.requests / wall
    chips = jax.device_count()
    misses = server.stats()["deadline_misses"] - misses_warm

    parity = None
    if args.parity:
        checked = 0
        for r in done[:: max(1, len(done) // 16)]:
            oracle = server.oracle(r.node, r.seq)
            if not np.array_equal(r.result, oracle):
                raise AssertionError(
                    f"parity violation: node {r.node} seq {r.seq} ladder "
                    f"response != direct oracle"
                )
            checked += 1
        parity = f"ok:{checked}"
        log(f"parity: {checked} responses bitwise equal to the oracle")
    if recompiles_steady:
        raise AssertionError(
            f"steady-state recompiles: {recompiles_steady} (ladder must "
            f"only replay after warmup)"
        )

    log(server.timeline.report())
    emit(
        "serve-latency",
        qps / chips,
        "qps/chip",
        None,
        arrival=args.arrival,
        max_batch=args.max_batch,
        p50_ms=round(p50, 3),
        p95_ms=round(p95, 3),
        p99_ms=round(p99, 3),
        slo_ms=args.slo_ms,
        p99_within_slo=bool(p99 <= args.slo_ms),
        deadline_miss_rate=round(misses / args.requests, 4),
        recompiles_steady=recompiles_steady,
        requests=args.requests,
        **({"parity": parity} if parity else {}),
        **({"rate_qps": args.rate} if args.arrival == "open" else {}),
    )


if __name__ == "__main__":
    main()
