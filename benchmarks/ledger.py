"""Persistent ledger of successful ``platform: tpu`` benchmark records.

Round-3 failure (VERDICT r3, "what's weak" #1): the round's best result —
the 9.70M SEPS real-TPU headline — existed only in a supervisor's scrollback
and hand-transcribed markdown, because the tunnel was dead at snapshot time
and the round-end ``BENCH_r03.json`` recorded the degraded CPU fallback.

The fix: every successful TPU measurement is appended to a committed ledger
(``docs/tpu_ledger.jsonl``) *at emit time, from inside the measured process*
(``benchmarks.common.emit``), so a supervisor timeout-kill or a later dead
tunnel can never erase it. The repo-root ``bench.py`` re-emits the last-good
ledger headline — labeled ``stale: <timestamp>`` — when a fresh attempt
degrades to the CPU fallback.

Reference counterpart: none — the reference's benchmark scripts
(e.g. /root/reference/benchmarks/sample/bench_sampler.py) print to stdout
and rely on an attended terminal; an unattended tunneled chip needs durable
evidence.
"""

from __future__ import annotations

import datetime
import json
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def path() -> str:
    """Ledger location (env-overridable for tests)."""
    return os.environ.get(
        "QUIVER_TPU_LEDGER",
        os.path.join(_REPO_ROOT, "docs", "tpu_ledger.jsonl"),
    )


def append(rec: dict) -> bool:
    """Persist ``rec`` iff it is a real, non-degraded TPU measurement.

    Adds a UTC ``ts`` stamp. fsync'd: the writing process may be
    timeout-killed moments later. Returns True when a line was written.
    """
    if rec.get("platform") != "tpu" or rec.get("degraded") or rec.get("stale"):
        return False
    row = dict(rec)
    row.setdefault(
        "ts",
        datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"),
    )
    try:
        os.makedirs(os.path.dirname(path()), exist_ok=True)
        with open(path(), "a") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        return False
    return True


def best_good(metric: str, min_nodes: int | None = None,
              **match) -> dict | None:
    """Highest-value ledger record for ``metric`` among full measurements.

    Smoke-scale rows are always skipped; when ``min_nodes`` is given, rows
    must carry ``nodes >= min_nodes`` (rows without a ``nodes`` stamp are
    rejected — the committed seed ledger stamps its rows). Max-by-value,
    not newest: a ``--dedup both`` run emits the winning variant first and
    the losing one last, so file order would resurface the loser.
    """
    best = None
    for rec in _rows():
        if rec.get("metric") != metric or rec.get("smoke"):
            continue
        if min_nodes is not None and not (
                isinstance(rec.get("nodes"), (int, float))
                and rec["nodes"] >= min_nodes):
            continue
        if any(rec.get(k) != v for k, v in match.items()):
            continue
        if best is None or (rec.get("value") or 0) > (best.get("value") or 0):
            best = rec
    return best


def _rows():
    try:
        with open(path()) as f:
            lines = f.readlines()
    except OSError:
        return
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            yield rec


def metrics_jsonl_path() -> str | None:
    """Location of the run's graftscope ``metrics.jsonl`` artifact.

    Set by the runner (``scripts/mega_session.py`` points it at
    ``<out>/metrics.jsonl``) or by hand via ``QUIVER_METRICS_JSONL``;
    ``None`` (unset/empty) disables the artifact — standalone bench runs
    must not silently grow files under docs/.
    """
    return os.environ.get("QUIVER_METRICS_JSONL") or None


def append_metrics(snapshots, extra: dict | None = None) -> int:
    """Append :class:`MetricSnapshot` rows to the metrics.jsonl artifact.

    Same durability discipline as :func:`append`: written from inside the
    measured process at emit time, best-effort (a full disk must not kill
    a measurement run). Returns the number of rows written (0 when the
    artifact is disabled)."""
    path = metrics_jsonl_path()
    snapshots = list(snapshots)
    if not path or not snapshots:
        return 0
    from quiver_tpu.obs.export import write_jsonl

    row = dict(extra or {})
    row.setdefault(
        "ts",
        datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"),
    )
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        return write_jsonl(snapshots, path, extra=row)
    except OSError:
        return 0


def read_metrics(path: str | None = None):
    """Parse a metrics.jsonl artifact back into snapshots (offline
    analysis twin of :func:`append_metrics`)."""
    from quiver_tpu.obs.export import read_jsonl

    p = path or metrics_jsonl_path()
    if not p or not os.path.exists(p):
        return []
    return read_jsonl(p)


def last_good(metric: str, **match) -> dict | None:
    """Most recent ledger record for ``metric`` whose fields equal ``match``.

    "Most recent" is file order (append-only), not ``ts`` — a re-seeded or
    hand-merged ledger still resolves deterministically.
    """
    best = None
    for rec in _rows():
        if rec.get("metric") != metric:
            continue
        if any(rec.get(k) != v for k, v in match.items()):
            continue
        best = rec
    return best
