"""Feature-collection throughput (GB/s) benchmark.

Methodology: GB/s = Σ gathered bytes / synchronized wall time, the
reference's benchmarks/feature/bench_feature.py:35-46. Ids are drawn
degree-skewed (high-degree nodes proportionally more often), matching what a
neighbor sampler actually requests — this is exactly the access pattern the
degree-ordered hot tier exploits (docs/Introduction_en.md:73-119).

Baseline: 14.82 GB/s = reference 1-GPU, ogbn-products, 20% cache, remainder
served over UVA from host memory (docs/Introduction_en.md:95).

Policies: ``replicate`` = hot tier replicated per device + pinned-host cold
tier (reference device_replicate); ``shard`` = hot tier sharded over the
mesh's feature axis with ICI-collective gathers (reference
p2p_clique_replicate; needs >1 device to mean anything).
"""

import time

import numpy as np

from benchmarks.common import (
    base_parser,
    build_graph,
    emit,
    log,
    run_guarded,
    write_metrics,
)

BASELINE_GBPS = 14.82


def main():
    p = base_parser(__doc__)
    p.add_argument("--feature-dim", type=int, default=100)  # products: 100 floats
    p.add_argument("--cache-ratio", type=float, default=0.2)
    p.add_argument("--policy", default="replicate", choices=["replicate", "shard"])
    p.add_argument("--gather-batch", type=int, default=65536)
    p.add_argument(
        "--kernel",
        default="auto",
        choices=["auto", "pallas", "xla"],
        help="hot-tier gather kernel (auto = pallas on TPU, xla elsewhere)",
    )
    p.add_argument(
        "--routed", action="store_true",
        help="shard policy: owner-routed all_to_all hot-tier gather (ids "
        "sharded over every mesh axis) instead of the psum flavor — the "
        "seed_sharding='all' trainer's gather",
    )
    p.add_argument(
        "--routed-alpha", type=float, default=0.0, metavar="A",
        help="capped-bucket factor for --routed: per-destination bucket "
        "capacity ceil(A*L/F), so each all_to_all hop moves ~A*L lanes "
        "instead of the exact-safe F*L; overflow is fallback-served and "
        "counted. 0 = uncapped full-length buckets",
    )
    p.add_argument(
        "--dtype", default="f32", choices=["f32", "bf16", "int8"],
        help="feature storage dtype: bf16 halves row bytes; int8 "
        "(per-row absmax quantization, dequant on gather) quarters them",
    )
    p.add_argument(
        "--store", default="ram", choices=["ram", "mmap", "pread"],
        help="feature residency: ram = the in-RAM tiered Feature; mmap/"
        "pread = the disk-backed MmapFeatureStore (quiver-ooc) with the "
        "cold tier window-read off a raw-format dir through the async "
        "stager — mmap maps the row file, pread uses positioned reads "
        "(bounded address space, the rlimit-drill mode). Gathers are "
        "bitwise-identical across all three; replicate policy only",
    )
    p.add_argument(
        "--ooc-window", type=int, default=4096, metavar="ROWS",
        help="--store mmap/pread: rows per disk read window (readahead "
        "granularity)",
    )
    p.add_argument(
        "--ooc-cache-windows", type=int, default=64, metavar="N",
        help="--store mmap/pread: stager LRU capacity in windows (bounds "
        "resident staging bytes at N * window * row bytes)",
    )
    p.add_argument(
        "--replicate-budget", default="0", metavar="BYTES",
        help="per-chip byte budget for the L0 replicated super-hot tier "
        "(same parser as device_cache_size, e.g. '16M'): the top-degree "
        "rows are replicated in every chip's HBM and served with ZERO "
        "interconnect lanes; the sharded tier only carries the remaining "
        "(1-h0) of the traffic, and the routed cap is tightened by the "
        "measured L0 hit rate. 0 = the two-tier (PR 1) path",
    )
    p.add_argument(
        "--controller", action="store_true",
        help="quiver-ctl lane (needs --policy shard and a nonzero "
        "--replicate-budget): replay a recorded skewed trace whose heat "
        "does NOT follow degree through the frequency sketch, re-tier L0 "
        "to the measured-hottest rows (ShardedFeature.repin), and emit "
        "the measured L0 hit-rate delta vs the static degree-prefix "
        "placement at the SAME budget, plus the audited JSONL "
        "decision-log path",
    )
    p.add_argument(
        "--stream", type=int, default=0, metavar="N",
        help="headline via a fused id stream: lax.scan over N pre-staged "
        "device id batches in ONE compiled program (ids come from the "
        "sampler on-device in real use — per-call H2D of each id batch "
        "measures the host link, not the gather). The per-call loop is "
        "still emitted as a dispatch=percall record",
    )
    p.set_defaults(iters=50, warmup=5)
    args = p.parse_args()
    if args.controller and args.policy != "shard":
        p.error("--controller requires --policy shard (repin is the "
                "sharded store's actuator)")
    if args.store != "ram":
        if args.policy != "replicate":
            p.error("--store mmap/pread requires --policy replicate (the "
                    "disk tier backs the replicated store's cold rows)")
        if args.stream:
            p.error("--store mmap/pread is eager (host-staged disk "
                    "reads); the fused --stream lane needs --store ram")
        if args.dtype == "bf16":
            p.error("--store mmap/pread supports f32 and int8 (the raw "
                    "writer mirrors Feature's quantize path)")
    run_guarded(lambda: _body(args), args)


def _body(args):
    import jax
    import jax.numpy as jnp

    from quiver_tpu import Feature, ShardedFeature
    from quiver_tpu.parallel.mesh import make_mesh

    topo = build_graph(args)
    n, f = topo.node_count, args.feature_dim
    feat = np.random.default_rng(args.seed).normal(size=(n, f)).astype(np.float32)
    budget = int(args.cache_ratio * n) * f * 4

    dtype = {"f32": None, "bf16": "bfloat16", "int8": "int8"}[args.dtype]
    if args.store != "ram":
        import os
        import tempfile

        from quiver_tpu.ooc import MmapFeatureStore

        raw_dir = os.path.join(
            tempfile.mkdtemp(prefix="quiver-ooc-bench-"), "rows"
        )
        t0 = time.time()
        MmapFeatureStore.write(raw_dir, feat, device_cache_size=budget,
                               csr_topo=topo, dtype=dtype)
        log(f"raw feature dir written in {time.time()-t0:.1f}s: {raw_dir}")
        store = MmapFeatureStore(
            raw_dir, kernel=args.kernel, access=args.store,
            window_rows=args.ooc_window,
            cache_windows=args.ooc_cache_windows,
        )
    elif args.policy == "replicate":
        store = Feature(
            device_cache_size=budget, csr_topo=topo, kernel=args.kernel,
            dtype=dtype, replicate_budget=args.replicate_budget,
        ).from_cpu_tensor(feat)
    else:
        mesh = make_mesh(feature=len(jax.devices()))
        store = ShardedFeature(
            mesh,
            device_cache_size=budget // len(jax.devices()),
            csr_topo=topo,
            kernel=args.kernel,
            dtype=dtype,
            routed_alpha=args.routed_alpha or 2.0,
            replicate_budget=args.replicate_budget,
        ).from_cpu_tensor(feat)
    del feat

    # degree-skewed id stream: P(node) ∝ degree — the sampler's access law
    rng = np.random.default_rng(args.seed + 1)
    deg = topo.degree.astype(np.float64)
    prob = deg / deg.sum()
    batches = [
        rng.choice(n, size=args.gather_batch, p=prob).astype(np.int32)
        for _ in range(min(args.iters, 8))  # reuse id sets; drawing is slow
    ]

    # capped-bucket routing: --routed-alpha > 0 pins cap = ceil(A*L/F) as
    # an EXPLICIT capacity (not "auto") so mid-run overflow is
    # fallback-served and reported rather than silently re-planned — the
    # emitted comm model must match what actually ran
    routed_cap, routed_model = _routed_comm_model(args, store)

    def fetch(ids):
        if args.routed:
            if args.policy != "shard":
                raise ValueError("--routed requires --policy shard")
            return store.gather(ids, routed=True, routed_cap=routed_cap)
        return store[ids]

    t0 = time.time()
    for i in range(args.warmup):
        res = fetch(jnp.asarray(batches[i % len(batches)]))
    jax.block_until_ready(res)
    log(f"warmup+compile: {time.time()-t0:.1f}s; hot ratio {store.cache_ratio:.2f}")

    # three-tier: the warmup measured the L0 hit rate; L0 lanes enter the
    # routed gather as invalid and occupy no bucket capacity, so the cap
    # can be tightened by (1-h0) — the sharded tier physically moves
    # ~alpha*L*(1-h0) lanes per hop instead of alpha*L. Re-plan, then pay
    # the one retrace outside the clock.
    h0 = _tier_hit_rates(store).get("hit_rep", 0.0)
    if h0 > 0 and routed_cap is not None:
        routed_cap, routed_model = _routed_comm_model(args, store, h0=h0)
        log(f"L0 hit rate {h0:.3f}: routed cap tightened to {routed_cap} "
            f"({routed_model['lanes_per_hop']} lanes/hop)")
        res = fetch(jnp.asarray(batches[0]))
        jax.block_until_ready(res)

    # count bytes PHYSICALLY moved by the gather: the stored dtype's row
    # bytes (+ the 4-byte dequant scale per row for int8) — int8's output
    # is dequantized f32, and counting that would inflate GB/s 4x
    stored_itemsize = np.dtype(store.dtype).itemsize
    row_overhead = 4 if args.dtype == "int8" else 0
    total_bytes = 0
    t0 = time.time()
    for i in range(args.iters):
        if args.store != "ram":
            # the training pipeline's overlap seam: batch i+1's cold
            # windows dispatch while batch i's gather runs
            store.prefetch(batches[(i + 1) % len(batches)])
        res = fetch(jnp.asarray(batches[i % len(batches)]))
        total_bytes += res.shape[0] * (
            res.shape[1] * stored_itemsize + row_overhead
        )
    jax.block_until_ready(res)
    dt = time.time() - t0

    percall_gbps = total_bytes / dt / 1e9

    if args.stream:
        # guarded: a stream failure must not discard the measured per-call
        # number (run_guarded would retry the whole body and degrade)
        try:
            _stream_gbps(args, store, batches, stored_itemsize, row_overhead,
                         routed_cap=routed_cap, routed_model=routed_model)
        except Exception as e:  # noqa: BLE001
            log(f"stream measure failed (per-call record stands): "
                f"{type(e).__name__}: {str(e)[:200]}")

    emit(
        "feature-collection-GBps/chip",
        percall_gbps,
        "GB/s",
        BASELINE_GBPS,
        policy=args.policy,
        kernel=store.kernel,
        dtype=args.dtype,
        cache_ratio=round(store.cache_ratio, 3),
        gather_batch=args.gather_batch,
        dispatch="percall",
        routed=getattr(args, "routed", False),
        store=args.store,
        **_tier_hit_rates(store),
        **_routed_extras(store, routed_model),
        **_ooc_extras(args, store),
    )
    # metrics.jsonl artifact: the store's registry snapshots (tier hits)
    # plus the hot tier's (routed overflow), attributed to this lane
    write_metrics(store, getattr(store, "hot", None),
                  lane="feature", policy=args.policy)

    if args.controller:
        _controller_lane(args, store, topo)


def _controller_lane(args, store, topo):
    """quiver-ctl replay: measured-frequency placement vs degree-static.

    The initial placement can only pin a degree-order PREFIX into L0;
    the controller re-tiers to the rows a trace actually hammers. The
    recorded trace is built so heat does NOT follow degree (80% of the
    mass on the LOWEST-degree rows — the pattern a degree prefix cannot
    see), replayed through the sketch, and ``maybe_repin`` re-tiers the
    live store. The record carries the trace-measured L0 hit rate
    before/after at the SAME replicate budget, the in-program tier hits
    of a post-repin device gather, and the audited decision-log path.
    """
    import os

    import jax
    import jax.numpy as jnp

    from benchmarks import ledger
    from quiver_tpu import CacheController
    from quiver_tpu.control.freq import FreqSketch

    n = store.shape[0]
    rep = store.rep_rows
    if rep <= 0:
        log("controller lane skipped: no L0 tier "
            "(--replicate-budget is 0 or degraded to cold-only)")
        return

    # recorded skewed trace, heat != degree: hot set = lowest-degree rows
    rng = np.random.default_rng(args.seed + 2)
    hot_k = min(rep, 1024)  # the sketch's exact heavy-hitter capacity
    hot = np.argsort(topo.degree.astype(np.int64), kind="stable")[:hot_k]
    trace = [
        np.where(
            rng.random(args.gather_batch) < 0.8,
            rng.choice(hot, size=args.gather_batch),
            rng.integers(0, n, args.gather_batch),
        ).astype(np.int32)
        for _ in range(4)
    ]

    def trace_l0_hit_rate():
        order = np.asarray(store.feature_order)
        hits = sum(int((order[b] < store.rep_rows).sum()) for b in trace)
        return hits / float(sum(b.size for b in trace))

    static_rate = trace_l0_hit_rate()
    mpath = ledger.metrics_jsonl_path()
    dlog = os.path.join(os.path.dirname(mpath) if mpath else ".",
                        "controller_decisions.jsonl")
    ctl = CacheController(sketch=FreqSketch(n, top_k=max(hot_k, 1024)),
                          decision_log=dlog)
    t0 = time.time()
    for batch in trace:
        ctl.observe_ids(batch)
    repinned = ctl.maybe_repin(store)
    measured_rate = trace_l0_hit_rate()
    log(f"controller lane: L0 hit rate {static_rate:.3f} -> "
        f"{measured_rate:.3f} (repin={repinned}, "
        f"{time.time() - t0:.1f}s observe+repin)")
    # one post-repin device gather: exercises the re-tiered tiers end to
    # end and lands the in-program tier hits in the record
    res = store[jnp.asarray(trace[0])]
    jax.block_until_ready(res)
    emit(
        "feature-controller-L0-hit-rate",
        measured_rate,
        "fraction",
        None,
        policy=args.policy,
        dtype=args.dtype,
        rep_rows=int(store.rep_rows),
        static_hit_rate=round(static_rate, 4),
        hit_rate_delta=round(measured_rate - static_rate, 4),
        repinned=repinned,
        pinned_hot_rows=int(hot_k),
        decisions=ctl.stats()["decisions"],
        decision_log=dlog,
        **_tier_hit_rates(store),
    )
    write_metrics(store, ctl, lane="feature-controller", policy=args.policy)


def _ooc_extras(args, store):
    """Ledger extras for a disk-backed (--store mmap/pread) run: the
    stager's lifetime read/readahead counters and the exposed blocking
    share of disk cost."""
    if args.store == "ram" or getattr(store, "stager", None) is None:
        return {}
    st = store.stager
    return {
        "ooc_window_rows": st.window_rows,
        "ooc_page_reads": st.page_reads_total,
        "ooc_readahead_hits": st.readahead_hits_total,
        "ooc_stage_wait_s": round(st.stage_wait_total, 4),
    }


def _routed_comm_model(args, store, h0: float = 0.0):
    """Per-device comm-volume model of the routed hot-tier gather.

    Lanes (feature-row slots) each all_to_all hop carries per device:
    ``F * L`` for the exact-safe full-length buckets, ``F * cap`` for
    capped buckets (``cap = ceil(alpha * L / F)`` => ``~alpha * L``), where
    L is the per-device request length after padding. The model is exact —
    bucket shapes are static — and the measured overflow count (fallback-
    served lanes) rides alongside it in the record.

    ``h0`` is the measured L0 (replicated-tier) hit rate: L0 lanes enter
    the routed gather as invalid and occupy no bucket capacity, so the cap
    shrinks to ``ceil(alpha * (1-h0) * L / F)`` and the effective per-hop
    volume to ``~alpha * L * (1-h0)`` — strictly below the two-tier capped
    path whenever the super-hot tier is catching traffic.

    Returns (explicit_cap_or_None, model_extras_dict_or_None).
    """
    if not getattr(args, "routed", False) or store.hot is None:
        return None, None
    import jax

    n_dev = len(jax.devices())
    batch = args.gather_batch
    local_len = (batch + (-batch) % n_dev) // n_dev
    F = store.hot.num_shards
    uncapped_lanes = F * local_len
    if not args.routed_alpha:
        return None, {
            "lanes_per_hop": uncapped_lanes,
            "lanes_per_hop_uncapped": uncapped_lanes,
            "comm_reduction": 1.0,
        }
    h0 = min(max(float(h0), 0.0), 1.0)
    alpha_eff = max(args.routed_alpha * (1.0 - h0), 1e-6)
    cap = store.hot.routed_cap(local_len, alpha_eff)
    extras = {
        "routed_alpha": args.routed_alpha,
        "routed_cap": cap,
        "lanes_per_hop": F * cap,
        "lanes_per_hop_uncapped": uncapped_lanes,
        "comm_reduction": round(uncapped_lanes / (F * cap), 2),
    }
    if h0 > 0:
        extras["l0_hit_rate"] = round(h0, 4)
        extras["effective_lanes_per_hop"] = round(
            args.routed_alpha * local_len * (1.0 - h0), 1
        )
    return cap, extras


def _tier_hit_rates(store):
    """Measured per-tier hit rates of the store's last eager gather, read
    from its graftscope registry (``feature.tier_hits``; {} for stores
    without a registry or before any eager batch)."""
    from quiver_tpu.obs.registry import TIER_HITS

    reg = getattr(store, "metrics", None)
    hits = reg.value(TIER_HITS) if hasattr(reg, "value") else None
    if hits is None:
        # duck-typed stores without a registry still surface the legacy
        # attribute (kept as a thin view on real stores)
        hits = getattr(store, "last_tier_hits", None)
    if hits is None:
        return {}
    h = np.asarray(hits).astype(np.float64)
    tot = h.sum()
    if tot <= 0:
        return {}
    return {
        "hit_rep": round(h[0] / tot, 4),
        "hit_sharded": round(h[1] / tot, 4),
        "hit_cold": round(h[2] / tot, 4),
    }


def _routed_extras(store, routed_model):
    """Ledger extras for a routed run: the comm model + the measured
    fallback-served overflow count of the last gather (from the hot
    tier's graftscope registry, ``feature.routed_overflow``)."""
    from quiver_tpu.obs.registry import ROUTED_OVERFLOW

    if routed_model is None:
        return {}
    extras = dict(routed_model)
    hot = getattr(store, "hot", None)
    snap = None if hot is None else hot.metrics.snapshot(ROUTED_OVERFLOW)
    extras["routed_overflow"] = 0 if snap is None else int(snap.numpy)
    return extras


def _stream_gbps(args, store, batches, stored_itemsize, row_overhead,
                 reps: int = 3, routed_cap=None, routed_model=None):
    """GB/s over a fused id stream: ONE compiled program scans pre-staged
    device id batches; a full-row checksum in the carry keeps every gathered
    column live (summing a slice would let XLA narrow the gather). Timed
    region = the scan + one scalar readback; ids are staged outside the
    clock because in real training they are sampler output already in HBM.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    id_mat = jnp.asarray(
        np.stack([batches[i % len(batches)] for i in range(args.stream)])
    )

    # the store is CLOSED OVER, not passed: Feature is a pytree but
    # ShardedFeature is not (its gather wraps a shard_map program); captured
    # device buffers are hoisted to program parameters either way, so one
    # code path serves both policies
    routed = getattr(args, "routed", False)

    @jax.jit
    def stream(ids_all):
        def step(carry, ids):
            rows = (
                store.gather(ids, routed=True, routed_cap=routed_cap)
                if routed else store[ids]
            )
            return carry + jnp.sum(rows.astype(jnp.float32)), None
        total, _ = lax.scan(step, jnp.float32(0), ids_all)
        return total

    def one_rep():
        t0 = time.time()
        float(stream(id_mat))
        dt = time.time() - t0
        nbytes = args.stream * args.gather_batch * (
            store.shape[1] * stored_itemsize + row_overhead
        )
        return nbytes / dt / 1e9

    t0 = time.time()
    one_rep()  # compile
    log(f"stream compile: {time.time()-t0:.1f}s ({args.stream} batches/scan)")
    gbps = float(np.median([one_rep() for _ in range(reps)]))
    extras = {}
    ceiling = _gather_ceiling_gbps(args, store, stored_itemsize, row_overhead)
    if ceiling is not None:
        extras = {"roofline_frac": round(gbps / ceiling, 3),
                  "ceiling_gbps": round(ceiling, 1)}
    emit(
        "feature-collection-GBps/chip",
        gbps,
        "GB/s",
        BASELINE_GBPS,
        policy=args.policy,
        kernel=store.kernel,
        dtype=args.dtype,
        cache_ratio=round(store.cache_ratio, 3),
        gather_batch=args.gather_batch,
        dispatch="stream",
        stream_batches=args.stream,
        routed=getattr(args, "routed", False),
        **extras,
        **_tier_hit_rates(store),
        **_routed_extras(store, routed_model),
    )


def _gather_ceiling_gbps(args, store, stored_itemsize, row_overhead):
    """HBM-traffic ceiling for the row gather, in COUNTED GB/s (counted
    bytes = stored row bytes, the number the headline reports).

    Per gathered row the chip must move: one 32-byte granule for the random
    row-start access, the stored row (contiguous read), the OUTPUT row
    write (f32-dequantized for int8 — 4 bytes/element regardless of the
    stored tier), and for int8 a granule for the per-row scale gather.
    Only meaningful when every row lives in this chip's HBM: with a cold
    tier the bound is the host link, and with a sharded table it is the
    ICI collective path — a made-up ceiling would flatter those numbers,
    so both cases emit none.
    """
    from benchmarks.common import hbm_bandwidth_gbps

    if store.cache_ratio < 1.0 or args.policy != "replicate":
        return None
    bw = hbm_bandwidth_gbps()
    if bw is None:
        return None
    dim = store.shape[1]
    stored_row = dim * stored_itemsize + row_overhead
    out_itemsize = 4 if args.dtype == "int8" else stored_itemsize
    traffic = 32 + stored_row + dim * out_itemsize
    if args.dtype == "int8":
        traffic += 32  # random access to the f32 dequant scale row
    return bw * stored_row / traffic


if __name__ == "__main__":
    main()
