"""Out-of-core drill: train under a HARD address-space budget with the
graph on disk (quiver_tpu/ooc/) — the papers100M-shaped evidence job.

The claim under test is the ooc tier's whole reason to exist: a training
epoch completes when the graph does NOT fit in memory. Enforced, not
asserted — the measured child process runs under ``RLIMIT_AS`` set to its
warmed-up ``VmSize`` plus a budget that is at most 1/4 of the on-disk
graph bytes, so eagerly materializing the feature table (or leaking
per-step allocations) kills the epoch with ``MemoryError`` instead of
quietly passing on a big machine.

Shape of the run (child process, 2-virtual-device CPU mesh):

1. build a synthetic graph + feature table, publish both through the raw
   on-disk format (``CSRTopo.save(format="raw")``,
   :meth:`MmapFeatureStore.write`), and drop the in-RAM copies;
2. reopen the topology memory-mapped and the rows in ``pread`` mode (an
   mmap of the rows file would count its full size against RLIMIT_AS —
   the pread path keeps address space O(window cache), which is the
   point);
3. warm up one DataParallelTrainer epoch (compiles the step), trace the
   SAME cached step on a probe batch group and gate graftmem's static
   peak estimate against the address budget about to be enforced (via
   ``CostModel.calibrate_hbm``/``predict_hbm`` — the drill fails by
   prediction before it can fail by rlimit kill), read ``VmSize`` from
   /proc/self/status, then ``setrlimit(RLIMIT_AS, VmSize + budget)``;
4. run the measured epochs under the limit and require: the epoch
   completes, ``ooc.readahead_hits > 0`` (the stager's window
   amortization did real work), and ``len(trainer._step_cache)`` is
   unchanged from warmup (zero steady-state recompiles).

The parent emits the scoreboard record (``feature-ooc`` row); RLIMIT_AS
is process-wide and irreversible-downward, which is why the measured
body lives in a subprocess.

    python -m benchmarks.ooc_drill --smoke
"""

import argparse
import json
import os
import subprocess
import sys
import time

from benchmarks import common

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the child's mesh: 2 virtual CPU devices (same shape as the CI smoke)
_CHILD_XLA = "--xla_force_host_platform_device_count=2"


def _parser():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--budget-mb", type=float, default=64.0,
                   help="address-space headroom granted ABOVE the "
                        "warmed-up VmSize; the on-disk graph is sized to "
                        ">= 4x this")
    p.add_argument("--feature-dim", type=int, default=128)
    p.add_argument("--avg-degree", type=int, default=10)
    p.add_argument("--hot-frac", type=float, default=0.1,
                   help="fraction of rows resident in the store's hot tier")
    p.add_argument("--local-batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=8,
                   help="train steps per epoch")
    p.add_argument("--epochs", type=int, default=2,
                   help="measured epochs run UNDER the rlimit")
    p.add_argument("--window-rows", type=int, default=1024)
    p.add_argument("--cache-windows", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=900.0,
                   help="parent-side hard timeout on the child")
    p.add_argument("--smoke", action="store_true",
                   help="small budget/graph: a CI runner finishes in ~1 min")
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    # accepted for common.run_guarded compatibility
    p.add_argument("--backend-retries", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--backend-retry-delay", type=float, default=5.0,
                   help=argparse.SUPPRESS)
    return p


def _apply_smoke(args):
    if args.smoke:
        args.budget_mb = min(args.budget_mb, 24.0)
        args.feature_dim = min(args.feature_dim, 96)
        args.steps = min(args.steps, 4)
        args.local_batch = min(args.local_batch, 64)


def _derived(args):
    """Graph sizing: rows alone must be >= 4x the budget (with ~5% slack
    so filesystem rounding can't drop the ratio below the gate)."""
    budget = int(args.budget_mb * 1024 * 1024)
    row_bytes = args.feature_dim * 4  # float32 rows
    nodes = -(-int(4.2 * budget) // row_bytes)
    return budget, nodes


def _vm_size_bytes() -> int:
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("VmSize:"):
                return int(line.split()[1]) * 1024
    raise RuntimeError("VmSize not found in /proc/self/status")


def _child(args) -> int:
    """The measured body. Runs with JAX_PLATFORMS=cpu and 2 virtual
    devices (parent-set env); everything after warmup runs under
    RLIMIT_AS."""
    import gc
    import resource
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from quiver_tpu import CSRTopo, GraphSageSampler, MmapFeatureStore
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.obs import MetricsRegistry, StepTimeline
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    budget, nodes = _derived(args)
    f = args.feature_dim
    rng = np.random.default_rng(args.seed)

    common.log(f"[child] graph: {nodes} nodes x {f} f32 features "
               f"({nodes * f * 4 / 1e6:.0f} MB rows), budget "
               f"{budget / 1e6:.0f} MB")
    topo = CSRTopo(edge_index=rng.integers(
        0, nodes, size=(2, args.avg_degree * nodes)).astype(np.int64))
    feat = rng.normal(size=(nodes, f)).astype(np.float32)
    labels = rng.integers(0, 4, nodes).astype(np.int32)
    hot_budget = int(args.hot_frac * nodes) * f * 4

    tmp = tempfile.mkdtemp(prefix="quiver-ooc-drill-")
    topo_dir = os.path.join(tmp, "topo")
    rows_dir = os.path.join(tmp, "rows")
    topo.save(topo_dir, format="raw")
    MmapFeatureStore.write(rows_dir, feat, device_cache_size=hot_budget,
                           csr_topo=topo)
    graph_bytes = nodes * f * 4 + topo.indices.nbytes + topo.indptr.nbytes
    assert graph_bytes >= 4 * budget, (graph_bytes, budget)
    del feat, topo
    gc.collect()

    # reopen everything disk-backed: mmap'd CSR, pread feature rows
    topo = CSRTopo.load(topo_dir, mmap=True)
    timeline = StepTimeline()
    metrics = MetricsRegistry()
    store = MmapFeatureStore(
        rows_dir, access="pread", window_rows=args.window_rows,
        cache_windows=args.cache_windows, metrics=metrics,
        timeline=timeline,
    )
    mesh = make_mesh(data=2, feature=1, devices=jax.devices()[:2])
    sampler = GraphSageSampler(topo, [5, 5], seed=3,
                               seed_capacity=args.local_batch)
    trainer = DataParallelTrainer(
        mesh, sampler, store, GraphSAGE(hidden=16, num_classes=4,
                                        num_layers=2),
        optax.sgd(1e-2), local_batch=args.local_batch,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    lab = jnp.asarray(labels)
    idx = rng.integers(0, nodes, args.steps * trainer.global_batch)

    t0 = time.time()
    params, opt, _, _ = trainer.train_epoch(
        params, opt, idx, lab, jax.random.PRNGKey(1),
        rng=np.random.default_rng(1),
    )
    warm_s = time.time() - t0
    cache_warm = len(trainer._step_cache)

    # graftmem gate: statically predict the step program's peak bytes
    # from the SAME cached jit the measured epochs will run (trace-only
    # — nothing executes) and require it to fit the address budget about
    # to be enforced, through the controller-facing CostModel surface.
    # A step that cannot fit fails here, by prediction, instead of an
    # opaque MemoryError mid-epoch under the rlimit.
    from types import SimpleNamespace

    from quiver_tpu.control.cost import CostModel
    from quiver_tpu.tools.audit import mem as graftmem

    probe = [
        SimpleNamespace(out=out_, x=store[out_.n_id])
        for out_ in (sampler.sample(np.asarray(blk))
                     for blk in trainer.seed_blocks(
                         idx[:trainer.global_batch]))
    ]
    caps, fanouts, xs, n_id, eis, bsz = trainer._stack(probe)
    step = trainer._compiled_step(caps, fanouts, xs.shape[-1])
    traced = step.trace(params, opt, xs, eis, n_id, bsz, lab,
                        jax.random.PRNGKey(9))
    est = graftmem.estimate_peak(traced.jaxpr)
    # est is per-device; every virtual device lives in THIS process, so
    # the address-space gate sees the whole mesh's residency
    predicted = est.peak_bytes * int(mesh.devices.size)
    del probe, xs, n_id, eis, bsz, traced

    vm = _vm_size_bytes()
    model = CostModel(local_len=args.local_batch, num_shards=1)
    model.calibrate_hbm({"ooc_step": predicted})
    fit = model.predict_hbm("ooc_step", budget_bytes=vm + budget)
    common.log(f"[child] graftmem: step peak {est.peak_bytes / 1e6:.1f} "
               f"MB/device ({predicted / 1e6:.1f} MB mesh-wide) vs "
               f"{(vm + budget) / 1e6:.0f} MB address budget")
    assert fit["fits"], (
        f"static step peak {predicted} B cannot fit the enforced "
        f"RLIMIT_AS {vm + budget} B (headroom {fit['headroom_bytes']})"
    )

    common.log(f"[child] warmup epoch {warm_s:.1f}s, VmSize "
               f"{vm / 1e6:.0f} MB; clamping RLIMIT_AS to +"
               f"{budget / 1e6:.0f} MB")
    _, hard = resource.getrlimit(resource.RLIMIT_AS)
    resource.setrlimit(resource.RLIMIT_AS, (vm + budget, hard))

    epoch_times = []
    for epoch in range(2, 2 + args.epochs):
        t0 = time.time()
        params, opt, loss, steps = trainer.train_epoch(
            params, opt, idx, lab, jax.random.PRNGKey(epoch),
            rng=np.random.default_rng(epoch),
        )
        epoch_times.append(time.time() - t0)
        assert steps == args.steps, f"epoch delivered {steps}/{args.steps}"
        assert np.isfinite(float(loss)), "rlimit'd epoch produced NaN loss"
    cache_after = len(trainer._step_cache)
    assert cache_after == cache_warm, \
        f"steady-state recompiles: {cache_warm} -> {cache_after}"
    hits = int(store.stager.readahead_hits_total)
    reads = int(store.stager.page_reads_total)
    assert hits > 0, "stager window amortization never fired"
    wait = timeline.summary().get("ooc.stage_wait")
    store.close()

    print(json.dumps({
        "ooc_drill": 1,
        "epoch_s": round(min(epoch_times), 3),
        "epochs": args.epochs,
        "steps": args.steps,
        "nodes": nodes,
        "feature_dim": f,
        "graph_bytes": int(graph_bytes),
        "budget_bytes": int(budget),
        "graph_over_budget": round(graph_bytes / budget, 2),
        "vm_warm_bytes": int(vm),
        "readahead_hits": hits,
        "page_reads": reads,
        "stage_wait_s": round(float(wait.total), 4) if wait else 0.0,
        "recompiles_steady": 0,
        "hot_rows": int(store.hot_rows),
        "predicted_peak_bytes": int(predicted),
    }), flush=True)
    return 0


def main():
    args = _parser().parse_args()
    _apply_smoke(args)
    if args.child:
        return _child(args)

    # parent: never touches jax itself — the measured body needs a fresh
    # process so RLIMIT_AS (irreversible-downward) dies with the child
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    def body():
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " " + _CHILD_XLA).strip()
        env["PYTHONPATH"] = (
            REPO + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else REPO
        )
        argv = [sys.executable, "-m", "benchmarks.ooc_drill", "--child"]
        for flag, val in (
            ("--budget-mb", args.budget_mb),
            ("--feature-dim", args.feature_dim),
            ("--avg-degree", args.avg_degree),
            ("--hot-frac", args.hot_frac),
            ("--local-batch", args.local_batch),
            ("--steps", args.steps),
            ("--epochs", args.epochs),
            ("--window-rows", args.window_rows),
            ("--cache-windows", args.cache_windows),
            ("--seed", args.seed),
        ):
            argv += [flag, str(val)]
        common.log(f"spawning rlimit'd child: {' '.join(argv[1:])}")
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=args.timeout, env=env, cwd=REPO)
        sys.stderr.write(r.stderr or "")
        rec = None
        for line in (r.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict) and cand.get("ooc_drill"):
                    rec = cand
        if r.returncode != 0 or rec is None:
            tail = (r.stderr or r.stdout or "").strip()[-400:]
            raise RuntimeError(
                f"ooc drill child failed (rc={r.returncode}): {tail}"
            )
        common.set_record_context(
            nodes=rec["nodes"], smoke=True if args.smoke else None
        )
        common.emit(
            "ooc-epoch-time", rec["epoch_s"], "s", None,
            store="pread",
            graph_bytes=rec["graph_bytes"],
            budget_bytes=rec["budget_bytes"],
            graph_over_budget=rec["graph_over_budget"],
            readahead_hits=rec["readahead_hits"],
            page_reads=rec["page_reads"],
            ooc_stage_wait_s=rec["stage_wait_s"],
            recompiles_steady=rec["recompiles_steady"],
            hot_rows=rec["hot_rows"],
            steps=rec["steps"],
            predicted_peak_bytes=rec.get("predicted_peak_bytes"),
        )
        common.log(
            f"OOC drill OK: {rec['graph_over_budget']}x graph-over-budget, "
            f"{rec['readahead_hits']} readahead hits, "
            f"{rec['page_reads']} page reads, 0 steady recompiles"
        )
        return 0

    return common.run_guarded(body, args)


if __name__ == "__main__":
    raise SystemExit(main())
