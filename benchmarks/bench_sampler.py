"""Sampling throughput (SEPS) benchmark — both topology placements.

Methodology: SEPS = Σ valid sampled edges / synchronized wall time, the
reference's benchmarks/sample/bench_sampler.py:33-43. Padded lanes are NOT
counted (BASELINE.md honesty rule, SURVEY §7.4.6). Modes:

* ``HBM`` — topology in device HBM (reference "GPU" mode).
* ``HOST`` — topology in pinned host memory with staged windows (reference
  "UVA" mode, sage_sampler.py:25-27); the beyond-HBM placement.

Baseline: 34.29M SEPS = reference 1-GPU UVA on ogbn-products [15,10,5]
(docs/Introduction_en.md:41).
"""

import time

import numpy as np

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded

BASELINE_UVA_SEPS = 34.29e6


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--mode", default="HBM", choices=["HBM", "HOST", "GPU", "UVA"])
    p.add_argument(
        "--kernel",
        default="xla",
        choices=["xla", "pallas"],
        help="sampling kernel: exact XLA stratified sampler or the Pallas "
        "windowed-DMA kernel (HBM mode, unweighted)",
    )
    p.set_defaults(warmup=25, iters=50)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    import jax

    from quiver_tpu import GraphSageSampler

    topo = build_graph(args)
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=args.batch,
        seed=args.seed, kernel=args.kernel,
    )
    rng = np.random.default_rng(args.seed)

    t0 = time.time()
    for _ in range(args.warmup):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        jax.block_until_ready(out.n_id)
    log(f"warmup+compile: {time.time()-t0:.1f}s")

    total_edges = 0
    t0 = time.time()
    for _ in range(args.iters):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        # one device->host scalar read per iter (sum folds on device)
        total_edges += int(sum(out.edge_counts))
    jax.block_until_ready(out.n_id)
    dt = time.time() - t0

    emit(
        "sampled-edges/sec/chip",
        total_edges / dt,
        "SEPS",
        BASELINE_UVA_SEPS,
        mode=args.mode,
        kernel=args.kernel,
        fanout=args.fanout,
        batch=args.batch,
    )


if __name__ == "__main__":
    main()
