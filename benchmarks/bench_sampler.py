"""Sampling throughput (SEPS) benchmark — both topology placements.

Methodology: SEPS = Σ valid sampled edges / synchronized wall time, the
reference's benchmarks/sample/bench_sampler.py:33-43. Padded lanes are NOT
counted (BASELINE.md honesty rule, SURVEY §7.4.6). Modes:

* ``HBM`` — topology in device HBM (reference "GPU" mode).
* ``HOST`` — topology in pinned host memory with staged windows (reference
  "UVA" mode, sage_sampler.py:25-27); the beyond-HBM placement.

Baseline: 34.29M SEPS = reference 1-GPU UVA on ogbn-products [15,10,5]
(docs/Introduction_en.md:41).
"""

import time

import numpy as np

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded

BASELINE_UVA_SEPS = 34.29e6


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--mode", default="HBM", choices=["HBM", "HOST", "GPU", "UVA"])
    p.add_argument(
        "--kernel",
        default="xla",
        choices=["xla", "pallas"],
        help="sampling kernel: exact XLA stratified sampler or the Pallas "
        "windowed-DMA kernel (HBM mode, unweighted)",
    )
    p.add_argument(
        "--caps",
        default="auto",
        choices=["auto", "worst"],
        help="frontier capacities: auto right-sizes every layer from the "
        "first batch's observed uniques (results stay exact — overflow "
        "triggers a regrow+resample); worst pads to the theoretical bound, "
        "which on a power-law graph means sorting node_count-sized arrays "
        "in every reindex (SURVEY §7.4.2)",
    )
    p.add_argument(
        "--stages",
        action="store_true",
        help="also emit a per-layer sample/reindex stage profile (one JSON "
        "line per stage) — the attribution the headline number needs when "
        "it falls short of baseline",
    )
    p.set_defaults(warmup=25, iters=50)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _stage_profile(args, sampler, topo, reps: int = 30):
    """Time each layer's sample and reindex stages as separate compiled
    programs on realistic frontier inputs (the fused program hides the
    split; this attributes it)."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu.ops.reindex import reindex_layer
    from quiver_tpu.ops.sample import sample_layer

    cap = args.batch
    caps = sampler._caps_for(cap)
    rng = np.random.default_rng(args.seed + 7)
    padded = np.full(cap, -1, dtype=np.int32)
    seeds = rng.integers(0, topo.node_count, args.batch)
    padded[: args.batch] = seeds
    cur = jnp.asarray(padded)
    cur_n = jnp.int32(args.batch)
    key = jax.random.PRNGKey(args.seed + 7)

    def timed(fn, *fn_args):
        out = fn(*fn_args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.time()
            out = fn(*fn_args)
            jax.block_until_ready(out)
            ts.append(time.time() - t0)
        ts = np.sort(ts)
        k = max(1, len(ts) // 10)
        return out, float(np.mean(ts[k:-k]) * 1e3)

    use_pallas = sampler.kernel == "pallas"
    if use_pallas:
        from quiver_tpu.ops.pallas.sample import (
            DEFAULT_WINDOW,
            sample_layer_windowed,
        )

        # same trace-time fallback rule the fused program applies
        use_pallas = sampler.topo.indices.shape[0] >= DEFAULT_WINDOW

    for l, k in enumerate(sampler.sizes):
        key, sub = jax.random.split(key)
        if use_pallas:
            f_sample = jax.jit(
                lambda t, c, n, kk, fan=k: sample_layer_windowed(
                    t, c, n, fan, kk
                )
            )
        else:
            f_sample = jax.jit(
                lambda t, c, n, kk, fan=k: sample_layer(t, c, n, fan, kk)
            )
        (nbr, counts), t_sample = timed(f_sample, sampler.topo, cur, cur_n, sub)
        f_reindex = jax.jit(
            lambda c, n, nb, fc=caps[l]: reindex_layer(c, n, nb, fc)
        )
        (frontier, n_frontier, _, _), t_reindex = timed(
            f_reindex, cur, cur_n, nbr
        )
        emit(
            "sampler-stage-ms",
            t_sample,
            "ms",
            None,
            layer=l,
            stage="sample",
            kernel="pallas" if use_pallas else "xla",
            fanout=k,
            frontier_in=int(cur.shape[0]),
        )
        emit(
            "sampler-stage-ms",
            t_reindex,
            "ms",
            None,
            layer=l,
            stage="reindex",
            frontier_cap=int(caps[l]),
        )
        cur, cur_n = frontier, n_frontier


def _body(args):
    import jax

    from quiver_tpu import GraphSageSampler

    topo = build_graph(args)
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=args.batch,
        seed=args.seed, kernel=args.kernel,
        frontier_caps="auto" if args.caps == "auto" else None,
    )
    rng = np.random.default_rng(args.seed)

    t0 = time.time()
    for _ in range(args.warmup):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        jax.block_until_ready(out.n_id)
    log(f"warmup+compile: {time.time()-t0:.1f}s")

    total_edges = 0
    t0 = time.time()
    for _ in range(args.iters):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        # one device->host scalar read per iter (sum folds on device)
        total_edges += int(sum(out.edge_counts))
    jax.block_until_ready(out.n_id)
    dt = time.time() - t0

    emit(
        "sampled-edges/sec/chip",
        total_edges / dt,
        "SEPS",
        BASELINE_UVA_SEPS,
        mode=args.mode,
        kernel=args.kernel,
        fanout=args.fanout,
        batch=args.batch,
        caps=args.caps,
    )

    if getattr(args, "stages", False):
        # the headline is already emitted — a stage-profile failure must
        # not take the run down (each stage is a fresh compile, each a
        # fresh chance at a transient backend error)
        try:
            _stage_profile(args, sampler, topo)
        except Exception as e:  # noqa: BLE001
            log(f"stage profile failed (headline unaffected): "
                f"{type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
