"""Sampling throughput (SEPS) benchmark — both topology placements.

Methodology: SEPS = Σ valid sampled edges / synchronized wall time, the
reference's benchmarks/sample/bench_sampler.py:33-43. Padded lanes are NOT
counted (BASELINE.md honesty rule, SURVEY §7.4.6). Modes:

* ``HBM`` — topology in device HBM (reference "GPU" mode).
* ``HOST`` — topology in pinned host memory with staged windows (reference
  "UVA" mode, sage_sampler.py:25-27); the beyond-HBM placement.

Baseline: 34.29M SEPS = reference 1-GPU UVA on ogbn-products [15,10,5]
(docs/Introduction_en.md:41).
"""

import time

import numpy as np

from benchmarks.common import (
    BASELINE_UVA_SEPS,
    base_parser,
    build_graph,
    emit,
    hbm_bandwidth_gbps,
    log,
    run_guarded,
    sampler_roofline,
    stream_seps,
    write_metrics,
)


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--mode", default="HBM", choices=["HBM", "HOST", "GPU", "UVA"])
    p.add_argument(
        "--kernel",
        default="xla",
        choices=["xla", "pallas", "fused", "auto"],
        help="sampling kernel: exact XLA stratified sampler, the fused "
        "Pallas megakernel ('pallas' and 'fused' are the same engine — "
        "one windowed-DMA kernel behind every variant, weighted and "
        "sharded included; 'fused' names the scoreboard lane), or 'auto' "
        "(measured election, QUIVER_SAMPLE_KERNEL overrides)",
    )
    p.add_argument(
        "--dedup",
        default="sort",
        choices=["sort", "map", "scan", "both"],
        help="reindex dedup strategy: stable-sort run-scan, the sort-free "
        "dense-map scatter-min (reference hash-table analogue), or the "
        "zero-scatter sort/cummax/gather 'scan'. 'both' (stream mode) "
        "measures ALL strategies in one process — sharing the device "
        "topology and the planned caps — and emits the faster stream "
        "record FIRST, so the headline self-selects the winning strategy "
        "on whatever backend it runs on",
    )
    p.add_argument(
        "--weighted", action="store_true",
        help="weight-proportional neighbor draws (inverse-CDF over per-row "
        "prefix weights) on exp(N(0,1)) synthetic edge weights — the path "
        "the reference plumbed but never shipped reachable "
        "(quiver.cu.hpp:240-272 commented out)",
    )
    p.add_argument(
        "--caps",
        default="auto",
        choices=["auto", "worst"],
        help="frontier capacities: auto right-sizes every layer from the "
        "first batch's observed uniques (results stay exact — overflow "
        "triggers a regrow+resample); worst pads to the theoretical bound, "
        "which on a power-law graph means sorting node_count-sized arrays "
        "in every reindex (SURVEY §7.4.2)",
    )
    p.add_argument(
        "--stages",
        action="store_true",
        help="also emit a per-layer sample/reindex stage profile (one JSON "
        "line per stage) — the attribution the headline number needs when "
        "it falls short of baseline",
    )
    p.add_argument(
        "--topo-sharding",
        default="replicated",
        choices=["replicated", "mesh"],
        dest="topo_sharding",
        help="topology placement: 'replicated' (every chip holds the full "
        "CSR — the reference's per-GPU device-resident registration) or "
        "'mesh' — the CSR partitioned across the mesh's feature axis "
        "(~1/F topology bytes per chip); each hop routes frontier "
        "vertices to their owning shard over capped-bucket all_to_all "
        "collectives (sampling/dist.py) and the record carries the exact "
        "lanes-per-hop comm model + the measured fallback overflow",
    )
    p.add_argument(
        "--routed-alpha",
        type=float,
        default=2.0,
        metavar="A",
        dest="routed_alpha",
        help="--topo-sharding mesh: capped-bucket factor — per-destination "
        "bucket capacity ceil(A*L/F) per hop, so each all_to_all moves "
        "~A*L lanes instead of F*L; 0 = uncapped full-length buckets. "
        "Overflow lanes are fallback-served (exact) and counted",
    )
    p.add_argument(
        "--stream",
        type=int,
        default=0,
        metavar="N",
        help="headline via a fused seed stream: lax.scan over N batches in "
        "ONE compiled program with in-program valid-edge tallies and a "
        "single scalar readback. The per-call loop (one dispatch + one "
        "host sync per batch) is still measured and emitted as a second "
        "record with dispatch=percall. On a tunneled single chip each "
        "host<->device sync costs ~90ms RTT while the per-batch sample "
        "compute is single-digit ms, so per-call SEPS measures the tunnel, "
        "not the TPU; the stream is also how the fused train step actually "
        "consumes the sampler (sample_padded inside the step program).",
    )
    p.set_defaults(warmup=25, iters=50)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _stage_profile(args, sampler, topo, reps: int = 30):
    """Time each layer's sample and reindex stages as separate compiled
    programs on realistic frontier inputs (the fused program hides the
    split; this attributes it)."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu.ops.reindex import reindex_layer
    from quiver_tpu.ops.sample import sample_layer

    cap = args.batch
    caps = sampler._caps_for(cap)
    rng = np.random.default_rng(args.seed + 7)
    padded = np.full(cap, -1, dtype=np.int32)
    seeds = rng.integers(0, topo.node_count, args.batch)
    padded[: args.batch] = seeds
    cur = jnp.asarray(padded)
    cur_n = jnp.int32(args.batch)
    key = jax.random.PRNGKey(args.seed + 7)

    def timed(fn, *fn_args):
        out = fn(*fn_args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.time()
            out = fn(*fn_args)
            jax.block_until_ready(out)
            ts.append(time.time() - t0)
        ts = np.sort(ts)
        k = max(1, len(ts) // 10)
        return out, float(np.mean(ts[k:-k]) * 1e3)

    use_pallas = sampler.kernel == "pallas"
    if use_pallas:
        from quiver_tpu.ops.pallas.fused import (
            DEFAULT_WINDOW,
            fused_sample_layer,
        )

        # same trace-time fallback rules the fused program applies
        E = int(sampler.topo.indices.shape[0])
        md = getattr(sampler.topo, "max_degree", None)
        use_pallas = (
            E >= DEFAULT_WINDOW
            and max(sampler.sizes) <= DEFAULT_WINDOW
            and not (sampler.weighted
                     and (md is None or md > DEFAULT_WINDOW))
        )

    weighted = sampler.weighted
    for l, k in enumerate(sampler.sizes):
        key, sub = jax.random.split(key)
        if use_pallas:
            f_sample = jax.jit(
                lambda t, c, n, kk, fan=k: fused_sample_layer(
                    t, c, n, fan, kk, weighted=weighted
                )
            )
        else:
            f_sample = jax.jit(
                lambda t, c, n, kk, fan=k: sample_layer(
                    t, c, n, fan, kk, weighted=weighted
                )
            )
        (nbr, counts), t_sample = timed(f_sample, sampler.topo, cur, cur_n, sub)
        # honor the sampler's dedup strategy (same node_bound rule as
        # multilayer_sample) so stage attribution matches the headline
        nb_bound = (
            int(sampler.topo.indptr.shape[0]) - 1
            if sampler.dedup == "map" else None
        )
        f_reindex = jax.jit(
            lambda c, n, nb, fc=caps[l]: reindex_layer(
                c, n, nb, fc, node_bound=nb_bound,
                scatter_free=(sampler.dedup == "scan"),
            )
        )
        (frontier, n_frontier, _, _), t_reindex = timed(
            f_reindex, cur, cur_n, nbr
        )
        emit(
            "sampler-stage-ms",
            t_sample,
            "ms",
            None,
            layer=l,
            stage="sample",
            kernel="pallas" if use_pallas else "xla",
            fanout=k,
            frontier_in=int(cur.shape[0]),
        )
        emit(
            "sampler-stage-ms",
            t_reindex,
            "ms",
            None,
            layer=l,
            stage="reindex",
            dedup=sampler.dedup,
            frontier_cap=int(caps[l]),
        )
        cur, cur_n = frontier, n_frontier


def _stream_seps(args, sampler, topo, reps: int = 3):
    """Fused-stream headline (see benchmarks.common.stream_seps).

    Methodology note: per-batch outputs (Adj stacks) are produced and
    discarded inside the scan — the sample + reindex compute that defines
    SEPS is all live (the tallies depend on it); only the final
    reshape/stack assembly is dead code. Timed wall includes the seed
    matrix H2D and the scalar readback. Valid edges only (BASELINE.md
    honesty rule).

    ``--dedup both``: extra samplers measure the dense-map and zero-scatter
    scan strategies in the same process (sharing the device topology and
    the already-planned caps); records are emitted fastest-first so the
    supervisor's first-SEPS-record headline self-selects the winner on
    this backend.
    """
    from quiver_tpu import GraphSageSampler

    cap = sampler._seed_capacity  # _body always sets seed_capacity=batch

    candidates = [(sampler.dedup, sampler)]
    if args.dedup == "both":
        for dedup in ("map", "scan"):
            other = GraphSageSampler(
                topo, args.fanout, mode=args.mode, seed_capacity=cap,
                seed=args.seed, kernel=sampler.kernel, dedup=dedup,
                weighted=sampler.weighted,
                frontier_caps=(
                    tuple(sampler._frontier_caps)
                    if sampler._frontier_caps is not None else None
                ),
                device_topo=sampler.topo,
            )
            candidates.append((dedup, other))

    results = []
    for dedup, s in candidates:
        # identical seed stream per candidate (a fresh rng from the same
        # seed): the winner must be decided by strategy, not draw variance
        rng = np.random.default_rng(args.seed + 13)
        try:
            res = stream_seps(s, topo.node_count, cap, args.stream, rng, reps)
        except Exception as e:  # noqa: BLE001 — one candidate must not
            # discard the other's measurement
            log(f"stream candidate dedup={dedup} failed: "
                f"{type(e).__name__}: {str(e)[:200]}")
            continue
        if res is not None:
            results.append((res[0], dedup, res))
    winner = None
    for seps, dedup, (_, oflo, stream) in sorted(results, reverse=True):
        # roofline sanity: how far from the chip's HBM ceiling this number
        # is, not just how far from a 2021 GPU's (VERDICT r3 item 2)
        extra = {}
        try:
            s_cand = next(s for d, s in candidates if d == dedup)
            rl = sampler_roofline(s_cand, args.batch, dedup)
            if rl is not None:
                extra = {
                    "roofline_ceiling_seps": round(rl[1]),
                    "roofline_frac": round(seps / rl[1], 3),
                    "roofline_model": "hbm-traffic lower bound "
                    f"({rl[0] / 1e6:.0f} MB/batch @ "
                    f"{hbm_bandwidth_gbps():g} GB/s)",
                }
        except Exception as e:  # noqa: BLE001 — analytics must not cost a record
            log(f"roofline estimate failed: {type(e).__name__}: {str(e)[:120]}")
        emit(
            "sampled-edges/sec/chip",
            seps,
            "SEPS",
            BASELINE_UVA_SEPS,
            mode=args.mode,
            kernel=args.kernel,
            fanout=args.fanout,
            batch=args.batch,
            caps=args.caps,
            dedup=dedup,
            weighted=getattr(args, "weighted", False),
            dispatch="stream",
            stream_batches=stream,
            overflow=oflo,
            **extra,
        )
        if winner is None:
            winner = dedup
    # the stage profile should attribute the HEADLINE strategy
    return next(
        (s for d, s in candidates if d == winner), sampler
    )


def _sharded_comm_model(sampler, seed_cap: int, caps) -> dict:
    """Exact per-device lanes-per-hop model of the mesh-sharded sampler.

    Hop ``l`` (seeds outward) routes a per-worker frontier of width
    ``S_l = (seed_cap, caps[0], ..., caps[-2])[l]`` through four
    ``all_to_all`` exchanges — ids out, degrees back, offsets out,
    ``(cap, k)`` neighbor blocks back — moving
    ``F * cap_l * (2 + 2 * k_l)`` lanes with capped buckets
    (``cap_l = ceil(alpha * S_l / F)``) vs ``F * S_l * (2 + 2 * k_l)``
    uncapped. A weighted sampler adds one f32 exchange per hop (row
    weight totals back: ``+F * cap_l`` lanes). Bucket shapes are static,
    so the model is exact; the measured fallback overflow rides
    alongside it in the record.
    """
    from quiver_tpu.sampling.dist import routed_sample_cap

    F = sampler.topo.num_shards
    alpha = sampler.routed_alpha
    extra = 1 if sampler.weighted else 0
    widths = (seed_cap,) + tuple(caps[:-1])
    lanes, lanes_unc, hop_caps = [], [], []
    for S_l, k in zip(widths, sampler.sizes):
        cap_l = routed_sample_cap(S_l, F, alpha) or S_l
        hop_caps.append(int(cap_l))
        lanes.append(F * cap_l * (2 + extra + 2 * k))
        lanes_unc.append(F * S_l * (2 + extra + 2 * k))
    model = {
        "topo_sharding": "mesh",
        "routed_alpha": alpha,
        "hop_caps": hop_caps,
        "lanes_per_hop": lanes,
        "lanes_per_hop_uncapped": lanes_unc,
        "comm_reduction": round(sum(lanes_unc) / max(sum(lanes), 1), 2),
    }
    plan = sampler.topo.plan
    model.update(
        topo_bytes_per_chip=plan["per_chip_bytes"],
        topo_bytes_replicated=plan["replicated_bytes"],
        topo_shrink=round(plan["shrink_factor"], 2),
    )
    return model


def _body_sharded(args):
    """--topo-sharding mesh lane: the distributed sampler over the CSR
    partitioned across the mesh's feature axis. SEPS methodology is
    unchanged (valid sampled edges / synchronized wall, per chip); the
    record adds the exact lanes-per-hop comm model and the measured
    per-hop fallback overflow (``last_sample_overflow``)."""
    import jax

    from quiver_tpu import GraphSageSampler
    from quiver_tpu.parallel.mesh import make_mesh

    if args.mode not in ("HBM", "GPU"):
        raise SystemExit("--topo-sharding mesh requires --mode HBM (each "
                         "shard's slice is device-resident — that is the "
                         "point)")
    if args.stream:
        log("WARNING: --stream is not supported with --topo-sharding mesh; "
            "measuring the per-call dispatch loop only")
    dedup = "sort" if args.dedup == "both" else args.dedup
    if args.dedup == "both":
        log("WARNING: --dedup both is a stream-mode comparison; "
            "--topo-sharding mesh measures dedup=sort only")

    topo = build_graph(args)
    if args.weighted:
        # sharded weighted draws: each shard ships its row-local
        # prefix-weight segments; the owner answers the inverse-CDF search
        w = np.exp(
            np.random.default_rng(args.seed + 5).normal(size=topo.edge_count)
        ).astype(np.float32)
        topo.set_edge_weight(w)
    F = len(jax.devices())
    mesh = make_mesh(data=1, feature=F)
    alpha = args.routed_alpha or None
    sampler = GraphSageSampler(
        topo, args.fanout, mode="HBM", seed=args.seed, dedup=dedup,
        kernel="pallas" if args.kernel == "fused" else args.kernel,
        topo_sharding="mesh", mesh=mesh, routed_alpha=alpha,
        weighted=args.weighted,
        frontier_caps="auto" if args.caps == "auto" else None,
    )
    W = sampler.workers
    rng = np.random.default_rng(args.seed)

    t0 = time.time()
    for _ in range(args.warmup):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        jax.block_until_ready(out.n_id)
    log(f"warmup+compile: {time.time()-t0:.1f}s")

    total_edges = 0
    t0 = time.time()
    for _ in range(args.iters):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        total_edges += int(sum(out.edge_counts))
    jax.block_until_ready(out.n_id)
    dt = time.time() - t0
    seps_chip = total_edges / dt / W

    per_worker = -(-args.batch // W)
    seed_cap = sampler._seed_capacity or max(
        _bench_round_up(per_worker, 128), 128
    )
    caps = sampler._caps_for(seed_cap)
    model = _sharded_comm_model(sampler, seed_cap, caps)
    # per-hop fallback overflow from the sampler's graftscope registry
    # (``sample.hop_overflow``) instead of poking the legacy attribute
    from quiver_tpu.obs.registry import SAMPLE_OVERFLOW

    snap = sampler.metrics.snapshot(SAMPLE_OVERFLOW)
    sample_overflow = (
        [int(v) for v in snap.numpy] if snap is not None
        else [0] * len(sampler.sizes)
    )
    emit(
        "sampled-edges/sec/chip",
        seps_chip,
        "SEPS",
        BASELINE_UVA_SEPS,
        mode="HBM",
        kernel=args.kernel,
        fanout=args.fanout,
        batch=args.batch,
        caps=args.caps,
        dedup=dedup,
        dispatch="percall",
        weighted=args.weighted,
        mesh_devices=W,
        seps_mesh_total=round(total_edges / dt),
        sample_overflow=sample_overflow,
        **model,
    )
    write_metrics(sampler, lane="sampler-sharded")


def _bench_round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _body(args):
    import jax

    from quiver_tpu import GraphSageSampler

    if getattr(args, "topo_sharding", "replicated") == "mesh":
        return _body_sharded(args)

    topo = build_graph(args)
    if args.weighted:
        # the fused megakernel serves weighted draws too (ISSUE 16): no
        # kernel restriction — the inverse-CDF walk runs in-kernel
        w = np.exp(
            np.random.default_rng(args.seed + 5).normal(size=topo.edge_count)
        ).astype(np.float32)
        topo.set_edge_weight(w)
    base_dedup = "sort" if args.dedup == "both" else args.dedup
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=args.batch,
        seed=args.seed, dedup=base_dedup,
        kernel="pallas" if args.kernel == "fused" else args.kernel,
        weighted=args.weighted,
        frontier_caps="auto" if args.caps == "auto" else None,
    )
    rng = np.random.default_rng(args.seed)

    t0 = time.time()
    for _ in range(args.warmup):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        jax.block_until_ready(out.n_id)
    log(f"warmup+compile: {time.time()-t0:.1f}s")

    n_compiled = len(sampler._compiled_cache)
    total_edges = 0
    t0 = time.time()
    for _ in range(args.iters):
        out = sampler.sample(rng.integers(0, topo.node_count, args.batch))
        # one device->host scalar read per iter (sum folds on device)
        total_edges += int(sum(out.edge_counts))
    jax.block_until_ready(out.n_id)
    dt = time.time() - t0
    percall_seps = total_edges / dt
    # steady state must never recompile: the warmup loop owns every
    # (seed_cap, caps) program this batch shape can demand
    recompiles_steady = len(sampler._compiled_cache) - n_compiled
    if recompiles_steady:
        log(f"WARNING: {recompiles_steady} steady-state recompile(s) — "
            "the sampler program must be compiled once per shape")

    stage_sampler = sampler
    if args.dedup == "both" and not args.stream:
        log("WARNING: --dedup both only compares under --stream; this run "
            "measures dedup=sort per-call only")
    if args.stream:
        # stream headline FIRST (the supervisor takes the first SEPS record
        # as the headline), per-call after as the dispatch=percall record.
        # Guarded: a stream failure must not discard the per-call number
        # already in hand (same discipline as _stage_profile below)
        try:
            stage_sampler = _stream_seps(args, sampler, topo) or sampler
        except Exception as e:  # noqa: BLE001
            log(f"stream measure failed (per-call record stands): "
                f"{type(e).__name__}: {str(e)[:200]}")

    emit(
        "sampled-edges/sec/chip",
        percall_seps,
        "SEPS",
        BASELINE_UVA_SEPS,
        mode=args.mode,
        kernel=args.kernel,
        fanout=args.fanout,
        batch=args.batch,
        caps=args.caps,
        dedup=base_dedup,
        weighted=args.weighted,
        dispatch="percall",
        recompiles_steady=recompiles_steady,
    )

    if getattr(args, "stages", False):
        # the headline is already emitted — a stage-profile failure must
        # not take the run down (each stage is a fresh compile, each a
        # fresh chance at a transient backend error)
        try:
            _stage_profile(args, stage_sampler, topo)
        except Exception as e:  # noqa: BLE001
            log(f"stage profile failed (headline unaffected): "
                f"{type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
