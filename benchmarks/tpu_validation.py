"""On-TPU kernel validation + head-to-head benchmarks (VERDICT r1 items 2-3).

Runs the Pallas kernels COMPILED on real TPU (CPU tests only ever interpret
them) and holds them to the same oracles as the XLA paths:

1. windowed Pallas sampler: validity oracle (membership / counts /
   per-row distinctness) + inclusion-frequency test on device;
2. Pallas row-gather: differential vs dense take;
3. SEPS head-to-head, Pallas vs XLA sampler, across fanouts;
4. feature GB/s head-to-head, Pallas vs XLA gather.

Prints one JSON line per measurement (benchmarks/common.py schema) so the
results can be pasted into docs verbatim.

    python -m benchmarks.tpu_validation            # full run (needs TPU)
    python -m benchmarks.tpu_validation --smoke    # small shapes
"""

import time

import numpy as np

from benchmarks.common import (
    apply_smoke,
    base_parser,
    emit,
    init_backend,
    log,
    run_guarded,
)


def validate_sampler_correctness(topo, dev, fanout, batch, seed):
    """Validity oracle on compiled-Pallas output (tests/test_pallas.py
    invariants, run on device instead of interpret mode)."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu.ops.pallas.sample import sample_layer_windowed

    indptr, indices = topo.indptr, topo.indices
    seeds = np.random.default_rng(seed).integers(
        0, topo.node_count, batch
    ).astype(np.int32)
    nbr, counts = sample_layer_windowed(
        dev, jnp.asarray(seeds), jnp.int32(batch), fanout, jax.random.PRNGKey(seed)
    )
    nbr, counts = np.asarray(nbr), np.asarray(counts)
    bad = 0
    for r in range(batch):
        s = seeds[r]
        row = set(indices[indptr[s]:indptr[s + 1]].tolist())
        deg = indptr[s + 1] - indptr[s]
        got = nbr[r][nbr[r] >= 0]
        ok = (
            counts[r] == min(deg, fanout)
            and len(got) == counts[r]
            and set(got.tolist()) <= row
        )
        bad += not ok
    return bad


def frequency_test(topo, dev, fanout, trials, seed):
    """Inclusion frequencies of one high-degree row's neighbors must be
    ~uniform (the windowed kernel is distribution-approximate for
    deg > window; measure the deviation instead of assuming)."""
    import jax
    import jax.numpy as jnp

    from quiver_tpu.ops.pallas.sample import sample_layer_windowed

    deg = np.diff(topo.indptr)
    row = int(np.argmax(deg))  # hottest row
    d = int(deg[row])
    seeds = jnp.full(128, row, jnp.int32)
    hits = np.zeros(d, np.int64)
    base = topo.indptr[row]
    pos_of = {int(v): i for i, v in enumerate(topo.indices[base:base + d])}
    for t in range(trials):
        nbr, _ = sample_layer_windowed(
            dev, seeds, jnp.int32(128), fanout, jax.random.PRNGKey(1000 + t)
        )
        got = np.asarray(nbr).reshape(-1)
        for v in got[got >= 0]:
            hits[pos_of[int(v)]] += 1
    expected = hits.sum() / d
    rel_dev = float(np.abs(hits - expected).max() / max(expected, 1))
    return d, rel_dev


def bench_seps(sampler_cls, topo, fanouts, batch, iters, seed, kernel):
    """Stream-dispatch SEPS (benchmarks.common.stream_seps): the xla-vs-
    pallas ratio must reflect kernel compute, not the ~90ms/iter tunnel
    sync a per-call loop would add identically to both sides.

    Returns (seps, overflow, stream_batches) or None (int32 guard)."""
    from benchmarks.common import stream_seps

    sampler = sampler_cls(
        topo, fanouts, seed_capacity=batch, seed=seed, kernel=kernel
    )
    rng = np.random.default_rng(seed)
    # iters is the stream length (smoke mode shrinks it); worst-case caps
    # are deterministic here, so no eager planning call is needed
    return stream_seps(sampler, topo.node_count, batch, iters, rng, reps=3)


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--trials", type=int, default=50)
    p.set_defaults(nodes=500_000, iters=30)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    dev0 = init_backend(retries=getattr(args, "backend_retries", 1))
    apply_smoke(args)
    on_tpu = dev0.platform == "tpu"
    if not on_tpu:
        log("WARNING: not on TPU — Pallas runs in interpret mode; numbers "
            "are NOT hardware results")

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    t0 = time.time()
    ei = generate_pareto_graph(args.nodes, args.avg_degree, seed=args.seed)
    topo = CSRTopo(edge_index=ei)
    del ei
    dev = topo.to_device()
    log(f"graph: {topo.node_count} nodes, {topo.edge_count} edges "
        f"({time.time() - t0:.1f}s)")

    # 1. compiled-sampler correctness
    bad = validate_sampler_correctness(topo, dev, 10, 256, args.seed)
    emit("pallas-sampler-invalid-rows", bad, "rows", None, batch=256, fanout=10)

    # 2. frequency deviation on the hottest row
    d, rel_dev = frequency_test(topo, dev, 8, min(args.trials, 50), args.seed)
    emit("pallas-sampler-freq-reldev", rel_dev, "ratio", None, row_degree=d)

    # 3. SEPS head-to-head. Off-TPU, the pallas side runs in interpret mode
    # — minutes-slow and meaningless as a perf number — so only the xla
    # control runs there (correctness sections above still exercise the
    # interpreted kernel)
    kernels = ("xla", "pallas") if on_tpu else ("xla",)
    for kernel in kernels:
        res = bench_seps(
            GraphSageSampler, topo, args.fanout, args.batch, args.iters,
            args.seed, kernel,
        )
        if res is not None:
            seps, oflo, stream = res
            emit("sampler-seps", seps, "SEPS", 34.29e6, kernel=kernel,
                 fanout=args.fanout, batch=args.batch, dispatch="stream",
                 stream_batches=stream, overflow=oflo)

    # 4. gather GB/s head-to-head — the same fused-scan micro-bench
    # kernel=auto's election runs (distinct id batches per scan step so the
    # gather can't be hoisted; one scalar readback), plus the election
    # verdict itself as a committed artifact
    from quiver_tpu.feature.feature import (
        _measure_gather_gbps,
        resolve_gather_kernel,
    )

    gbps = {}
    for name in kernels:
        try:
            gbps[name] = _measure_gather_gbps(name)
        except Exception as e:  # noqa: BLE001 — one kernel's failure is a
            # result, not a reason to lose the other's number
            log(f"gather micro-bench {name} failed: "
                f"{type(e).__name__}: {str(e)[:200]}")
            continue
        emit("gather-GBps", gbps[name], "GB/s", 14.82, kernel=name,
             gather_batch=8192, feature_dim=128, dispatch="stream")
    elected = resolve_gather_kernel("auto")
    emit("gather-kernel-elected", gbps.get(elected, 0.0), "GB/s", None,
         elected=elected,
         measured={k: round(v, 2) for k, v in gbps.items()})


if __name__ == "__main__":
    main()
