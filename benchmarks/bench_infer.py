"""Full-graph layer-wise inference throughput benchmark.

Measures the whole-graph evaluation path (models/inference.py — the
reference's ``model.inference``, examples/pyg/reddit_quiver.py:68-92): a
complete multi-layer pass over EVERY node using ALL edges, as chunked
segment aggregation — any of the homogeneous families (--model
sage|gcn|gin|gat). Metric: nodes/s of finished final-layer embeddings
(= N / wall for the full multi-layer pass); extras carry the per-pass edge
throughput. No reference number exists (it never benchmarked inference);
this row tracks the framework's own capability.
"""

import time

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded


def main():
    p = base_parser(__doc__)
    p.add_argument("--feature-dim", type=int, default=100)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=47)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--chunk", type=int, default=None,
                   help="edges per aggregation program (default: each "
                   "family's tuned default — GAT halves it for its "
                   "per-chunk (chunk, heads, F) buffers)")
    p.add_argument("--mode", default="HBM", choices=["HBM", "HOST"])
    p.add_argument("--model", default="sage",
                   choices=["sage", "gcn", "gin", "gat"])
    p.add_argument("--heads", type=int, default=4, help="GAT heads")
    p.set_defaults(iters=3, warmup=1)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    import numpy as np

    import jax

    import jax.numpy as jnp

    from benchmarks.common import model_from_name
    from quiver_tpu.parallel.train import empty_adjs, init_model

    topo = build_graph(args)
    n = topo.node_count
    x_all = np.random.default_rng(args.seed).normal(
        size=(n, args.feature_dim)
    ).astype(np.float32)
    model, infer, edge_sweeps = model_from_name(
        args.model, args.hidden, args.classes, args.layers, heads=args.heads)

    # params from empty-Adj shapes (the trainer's init path) — flax only
    # needs static shapes, so no throwaway sampler + 128-seed sample
    adjs = empty_adjs([5] * args.layers, batch=8, node_count=n)
    x0 = jnp.zeros((adjs[0].size[0], args.feature_dim), jnp.float32)
    params = init_model(model, jax.random.PRNGKey(0), x0, adjs)

    t0 = time.time()
    for _ in range(max(args.warmup, 1)):  # >= 1: the first pass compiles
        logp = infer(model, params, topo, x_all, mode=args.mode,
                     **({"chunk": args.chunk} if args.chunk else {}))
    jax.block_until_ready(logp)
    log(f"warmup+compile: {time.time() - t0:.1f}s")

    t0 = time.time()
    for _ in range(args.iters):
        logp = infer(model, params, topo, x_all, mode=args.mode,
                     **({"chunk": args.chunk} if args.chunk else {}))
    jax.block_until_ready(logp)
    dt = time.time() - t0

    per_pass = dt / args.iters
    emit(
        "layerwise-inference-nodes/sec",
        n / per_pass,
        "nodes/s",
        None,
        mode=args.mode,
        model=args.model,
        layers=args.layers,
        pass_seconds=round(per_pass, 3),
        edges_per_sec=round(
            edge_sweeps * args.layers * topo.edge_count / per_pass, 1),
    )


if __name__ == "__main__":
    main()
