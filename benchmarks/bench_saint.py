"""GraphSAINT subgraph-sampling throughput benchmark.

No reference baseline exists (torch-quiver's ``qv.saint_subgraph`` never
landed — rotted stubs, SURVEY §2.5); this tracks the framework's own SAINT
capability after the round-3 devicification (VERDICT r2 item 5): each
``sample()`` is ONE compiled program (draw → masked_unique dedup → induced
subgraph), so the measured rate is pure device throughput with a single
host sync per draw.

Metrics: subgraphs/sec and induced edges/sec for the chosen sampler.
"""

import time

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded


def main():
    p = base_parser(__doc__)
    p.add_argument("--sampler", default="node", choices=["node", "edge", "rw"])
    p.add_argument("--budget", type=int, default=4096,
                   help="node budget (node), edge budget (edge)")
    p.add_argument("--roots", type=int, default=1024)
    p.add_argument("--walk-length", type=int, default=3)
    p.set_defaults(nodes=500_000, iters=50, warmup=5)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    import jax

    from quiver_tpu.sampling.saint import (
        SAINTEdgeSampler,
        SAINTNodeSampler,
        SAINTRandomWalkSampler,
    )

    topo = build_graph(args)
    if args.sampler == "node":
        s = SAINTNodeSampler(topo, budget=args.budget, seed=args.seed)
    elif args.sampler == "edge":
        s = SAINTEdgeSampler(topo, budget=args.budget, seed=args.seed)
    else:
        s = SAINTRandomWalkSampler(
            topo, roots=args.roots, walk_length=args.walk_length,
            seed=args.seed,
        )

    t0 = time.time()
    for _ in range(max(args.warmup, 1)):  # >= 1: the first call compiles
        sub = s.sample()
    jax.block_until_ready(sub.node_id)
    log(f"warmup+compile: {time.time() - t0:.1f}s; deg_cap={s.deg_cap}")

    total_edges = 0
    t0 = time.time()
    for _ in range(args.iters):
        sub = s.sample()
        total_edges += int(sub.num_edges)  # one scalar sync per draw
    jax.block_until_ready(sub.node_id)
    dt = time.time() - t0

    emit(
        "saint-subgraphs/sec",
        args.iters / dt,
        "subgraphs/s",
        None,
        sampler=args.sampler,
        induced_edges_per_sec=round(total_edges / dt, 1),
        budget=s.budget,
        deg_cap=s.deg_cap,
    )


if __name__ == "__main__":
    main()
