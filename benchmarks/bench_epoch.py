"""End-to-end training epoch benchmark (sample → gather → train step).

Methodology: iteration time × iterations-per-epoch, the reference's epoch
accounting (benchmarks/ogbn-papers100M/
dist_sampling_ogb_paper100M_quiver.py:159-165). Two estimators:

* default (``--prefetch 2``): steady-state wall / iters with the Prefetcher
  overlapping batch i+1's sample+gather under batch i's step — the analogue
  of the reference's DataLoader-worker prefetching, which its measured
  loops always ran with;
* ``--prefetch 0``: fully serial, 10%-trimmed-mean per-iteration time (the
  reference drops the first epoch and averages the rest; per-iteration
  trimming is the same idea at iter scale).

Workload mirrors the reference's headline e2e config
(docs/Introduction_en.md:146-149): products-scale graph, 3-layer GraphSAGE
fanout [15,10,5], batch 1024, feature dim 100, hidden 256, 20% feature
cache.

Baseline: 11.1 s/epoch = reference Quiver 1-GPU ogbn-products
(docs/Introduction_en.md:146-149). ``vs_baseline`` is reported as
baseline/ours (so >1 = faster than the reference).
"""

import time

import numpy as np

from benchmarks.common import (
    PRODUCTS_TRAIN_NODES,
    base_parser,
    build_graph,
    emit,
    log,
    run_guarded,
    trimmed_mean,
    write_metrics,
)

BASELINE_EPOCH_S = 11.1


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--feature-dim", type=int, default=100)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--classes", type=int, default=47)
    p.add_argument("--cache-ratio", type=float, default=0.2)
    p.add_argument("--model", default="sage",
                   choices=["sage", "gat", "gcn", "gin"])
    p.add_argument(
        "--mode",
        default="HBM",
        choices=["HBM", "HOST", "GPU", "UVA"],
        help="topology placement: HBM-resident or beyond-HBM host staging",
    )
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--train-nodes", type=int, default=PRODUCTS_TRAIN_NODES)
    p.add_argument(
        "--fused", action="store_true",
        help="ONE XLA program per step (DistributedTrainer on the device "
        "mesh): sample + gather + fwd/bwd + update with zero host "
        "round-trips. Works at any --cache-ratio and --mode: cold-tier "
        "rows and HOST topologies stage through host compute inside the "
        "same program. At --cache-ratio 1.0 compare the reference's 'PyG "
        "with full feature on GPU' rows (Introduction_en.md:153-158)",
    )
    p.add_argument(
        "--scan-epoch", action="store_true",
        help="the WHOLE epoch as one compiled program (epoch_scan: lax.scan "
        "over packed seed blocks, params in carry, one loss readback). "
        "Measures real epoch wall time directly instead of extrapolating "
        "iteration time — the TPU-native epoch loop. Implies --fused "
        "(and like it, accepts cold tiers and HOST topologies)",
    )
    p.add_argument(
        "--pipeline", action="store_true",
        help="software-pipelined epoch (DistributedTrainer "
        "pipeline_depth=1, one-step skew: batch t+1's sample+gather "
        "issued under batch t's fwd/bwd). ONE invocation measures the "
        "serial stage estimator (sample/gather/train_step Timer stages), "
        "the Prefetcher-overlapped host loop, the serial epoch_scan, and "
        "the pipelined epoch_scan, and emits all four ledger records "
        "side-by-side — overlap efficiency = serial stage-sum p50 / "
        "pipelined per-step p50 (>1.0 = sample+gather latency hidden "
        "under compute). Bitwise-identical losses to the serial scan "
        "(tests/test_pipelined_epoch.py)",
    )
    p.add_argument(
        "--seed-sharding", default="data", choices=["data", "all"],
        help="fused/scan modes: seed-block placement (see "
        "DistributedTrainer) — 'all' makes every device a data worker "
        "with the routed all_to_all sharded gather; only differs from "
        "'data' when the mesh's feature axis > 1",
    )
    p.add_argument(
        "--bf16", action="store_true",
        help="bfloat16 feature storage + mixed-precision model compute "
        "(f32 params, bf16 MXU matmuls) — the TPU-first precision recipe "
        "the fp32-only reference has no analogue of",
    )
    p.add_argument(
        "--prefetch", type=int, default=2,
        help="batches in flight beyond the current one (Prefetcher depth) — "
        "the analogue of the reference's DataLoader worker prefetching; "
        "0 = fully serial sample->gather->step",
    )
    p.set_defaults(batch=1024, iters=40, warmup=3)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.train import make_train_step

    topo = build_graph(args)
    n = topo.node_count
    feat = np.random.default_rng(args.seed).normal(size=(n, args.feature_dim))
    feat = feat.astype(np.float32)
    if args.scan_epoch:
        args.fused = True
    # fused/scan modes accept cold tiers and HOST topologies since r4: the
    # staged host gathers compose into the shard_map program
    itemsize = 2 if args.bf16 else 4  # budget in STORAGE bytes, so the
    # requested cache-ratio holds regardless of dtype tier
    budget = int(args.cache_ratio * n) * args.feature_dim * itemsize
    feature = Feature(
        device_cache_size=budget, csr_topo=topo,
        dtype="bfloat16" if args.bf16 else None,
    ).from_cpu_tensor(feat)
    del feat
    if abs(feature.cache_ratio - args.cache_ratio) > 0.01:
        log(f"actual hot ratio {feature.cache_ratio:.3f} "
            f"(requested {args.cache_ratio})")
    args.cache_ratio = round(feature.cache_ratio, 3)  # records report ACTUAL
    labels_all = jnp.asarray(
        np.random.default_rng(1).integers(0, args.classes, n).astype(np.int32)
    )

    dtype = "bfloat16" if args.bf16 else None
    from benchmarks.common import model_from_name

    model, _, _ = model_from_name(args.model, args.hidden, args.classes,
                                  len(args.fanout), heads=args.heads,
                                  dtype=dtype)
    tx = optax.adam(1e-3)
    rng = np.random.default_rng(args.seed + 1)

    if args.pipeline:
        _pipeline_measure(args, topo, feature, model, tx, labels_all, rng)
        return
    if args.scan_epoch:
        _scan_epoch_measure(args, topo, feature, model, tx, labels_all, rng)
        return
    if args.fused:
        # dispatch BEFORE constructing the serial sampler: its __init__
        # eagerly device-places a full topology copy the fused path would
        # never use (doubling topology HBM on top of the full-resident
        # feature table)
        iter_s, loss = _fused_measure(args, topo, feature, model, tx,
                                      labels_all, rng)
        _emit_epoch(args, iter_s, loss, fused=True)
        return

    # auto caps right-size every frontier to observed uniques — without this
    # the deepest n_id is worst-case-padded and the feature gather + model
    # aggregate run ~3x wider than needed (SURVEY §7.4.2)
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=args.batch,
        seed=args.seed, frontier_caps="auto",
    )
    step = jax.jit(make_train_step(model, tx))

    # graftscope stage attribution for the serial estimator: each stage is
    # Timer-fed into a StepTimeline (block_until_ready sync points), so the
    # run reports p50/p95/p99 per stage alongside the headline. The synced
    # boundaries only move where the serial chain waits, not how long the
    # whole iteration takes.
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.utils.trace import Timer

    timeline = StepTimeline()

    def iteration(params, opt_state, key):
        seeds = rng.integers(0, n, args.batch)
        with Timer("sample", quiet=True, registry=timeline):
            out = sampler.sample(seeds)
            jax.block_until_ready(out.n_id)
        with Timer("gather", quiet=True, registry=timeline):
            x = feature[out.n_id]
            jax.block_until_ready(x)
        seed_ids = out.n_id[: args.batch]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = seed_ids >= 0
        with Timer("train_step", quiet=True, registry=timeline):
            res = step(params, opt_state, x, out.adjs, labels, mask, key)
            jax.block_until_ready(res[2])
        return res

    # init + warmup (includes all compiles)
    out0 = sampler.sample(rng.integers(0, n, args.batch))
    x0 = feature[out0.n_id]
    params = model.init({"params": jax.random.PRNGKey(0)}, x0, out0.adjs)["params"]
    opt_state = tx.init(params)
    t0 = time.time()
    for i in range(max(args.warmup, 1)):  # >= 1: the first call compiles
        params, opt_state, loss = iteration(params, opt_state, jax.random.PRNGKey(i))
    jax.block_until_ready(loss)
    log(f"warmup+compile: {time.time()-t0:.1f}s")

    if args.prefetch > 0:
        # overlapped pipeline: batch i+1's sample+gather (incl. HOST-mode
        # host staging) runs under batch i's device step. Per-iter trimming
        # is meaningless here (latency hides across iters); steady-state
        # wall / iters is the honest number.
        from quiver_tpu import Prefetcher

        seed_stream = [rng.integers(0, n, args.batch)
                       for _ in range(args.iters)]
        pf = Prefetcher(sampler, feature, depth=args.prefetch)
        t0 = time.time()
        for i, batch in enumerate(pf.run(seed_stream)):
            seed_ids = batch.out.n_id[: args.batch]
            labels = labels_all[jnp.clip(seed_ids, 0)]
            mask = seed_ids >= 0
            params, opt_state, loss = step(
                params, opt_state, batch.x, batch.out.adjs, labels, mask,
                jax.random.PRNGKey(100 + i),
            )
        jax.block_until_ready(loss)
        iter_s = (time.time() - t0) / args.iters
    else:
        times = []
        for i in range(args.iters):
            t0 = time.time()
            params, opt_state, loss = iteration(
                params, opt_state, jax.random.PRNGKey(100 + i)
            )
            jax.block_until_ready(loss)
            times.append(time.time() - t0)

        iter_s = trimmed_mean(times)
        log("stage timeline (serial estimator):\n" + timeline.report())
    _emit_epoch(args, iter_s, loss, fused=False)


def _fused_measure(args, topo, feature, model, tx, labels_all, rng):
    """DistributedTrainer path: the whole iteration is ONE compiled program
    (sample -> gather -> fwd/bwd -> update), measured like the serial loop."""
    import jax

    from quiver_tpu import DistributedTrainer, GraphSageSampler
    from quiver_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, make_mesh

    n = topo.node_count
    mesh = make_mesh()
    workers = mesh.shape[DATA_AXIS] * (
        mesh.shape[FEATURE_AXIS] if args.seed_sharding == "all" else 1
    )
    # ceil: shard_seeds' first blocks get ceil(batch/workers) seeds
    local_batch = -(-args.batch // workers)
    # a dedicated sampler sized to the PER-DEVICE block, with auto caps
    # planned from a local-batch draw — planning at the global batch would
    # leave every device running frontiers ~worker-count too wide
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=local_batch,
        seed=args.seed, frontier_caps="auto",
    )
    sampler.sample(rng.integers(0, n, local_batch))
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, tx, local_batch=local_batch,
        seed_sharding=args.seed_sharding,
    )
    params, opt_state = trainer.init(jax.random.PRNGKey(0))

    t0 = time.time()
    for i in range(max(args.warmup, 1)):  # >= 1: the first step compiles
        params, opt_state, loss = trainer.step(
            params, opt_state, rng.integers(0, n, args.batch), labels_all,
            jax.random.PRNGKey(i),
        )
    jax.block_until_ready(loss)
    log(f"fused warmup+compile: {time.time() - t0:.1f}s")

    times = []
    for i in range(args.iters):
        t0 = time.time()
        params, opt_state, loss = trainer.step(
            params, opt_state, rng.integers(0, n, args.batch), labels_all,
            jax.random.PRNGKey(100 + i),
        )
        jax.block_until_ready(loss)
        times.append(time.time() - t0)
    # graftscope: the fused step's telemetry (registry snapshots + the
    # trainer's own stage timeline) — one-call summary in the log, the
    # snapshots appended to the run's metrics.jsonl artifact
    log(trainer.metrics_report())
    write_metrics(trainer, lane="epoch-fused")
    return trimmed_mean(times), loss


def _scan_epoch_measure(args, topo, feature, model, tx, labels_all, rng,
                        epochs: int = 3):
    """Measure REAL epoch wall time: the whole epoch is one compiled
    program (DistributedTrainer.epoch_scan), so the number is a direct
    measurement — pack + H2D of the epoch's seed matrix, the scan, and the
    loss-vector readback all inside the clock — not an iteration-time
    extrapolation."""
    import jax

    from quiver_tpu import DistributedTrainer, GraphSageSampler
    from quiver_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, make_mesh

    n = topo.node_count
    mesh = make_mesh()
    workers = mesh.shape[DATA_AXIS] * (
        mesh.shape[FEATURE_AXIS] if args.seed_sharding == "all" else 1
    )
    local_batch = -(-args.batch // workers)
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=local_batch,
        seed=args.seed, frontier_caps="auto",
    )
    sampler.sample(rng.integers(0, n, local_batch))
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, tx, local_batch=local_batch,
        seed_sharding=args.seed_sharding,
    )
    params, opt_state = trainer.init(jax.random.PRNGKey(0))
    train_idx = rng.permutation(n)[: args.train_nodes]

    t0 = time.time()
    seed_mat = trainer.pack_epoch(train_idx, key=0)
    params, opt_state, losses = trainer.epoch_scan(
        params, opt_state, seed_mat, labels_all, jax.random.PRNGKey(1)
    )
    jax.block_until_ready(losses)
    steps = int(seed_mat.shape[0])
    log(f"scan-epoch warmup+compile: {time.time() - t0:.1f}s "
        f"({steps} steps/epoch)")

    times = []
    for e in range(epochs):
        t0 = time.time()
        seed_mat = trainer.pack_epoch(train_idx, key=e + 1)
        params, opt_state, losses = trainer.epoch_scan(
            params, opt_state, seed_mat, labels_all,
            jax.random.PRNGKey(2 + e),
        )
        final_loss = float(losses[-1])  # readback inside the clock
        times.append(time.time() - t0)
    epoch_s = trimmed_mean(times)
    emit(
        "e2e-epoch-time",
        epoch_s,
        "s",
        BASELINE_EPOCH_S,
        invert=True,
        iter_ms=round(epoch_s / steps * 1e3, 2),
        iters_per_epoch=steps,
        batch=args.batch,
        model=args.model,
        mode="FUSED-SCAN",
        topo_mode=args.mode,
        seed_sharding=args.seed_sharding,
        bf16=bool(args.bf16),
        cache_ratio=args.cache_ratio,
        train_nodes=args.train_nodes,
        measured="direct",
        loss=round(final_loss, 4),
    )
    log(trainer.metrics_report())
    write_metrics(trainer, lane="epoch-scan")


def _pipeline_measure(args, topo, feature, model, tx, labels_all, rng,
                      epochs: int = 3):
    """The software-pipelined epoch vs its serial baselines, all measured
    in ONE invocation so the scoreboard row carries them side-by-side:

    1. serial stage estimator — eager sample -> gather -> train_step with
       Timer-fed StepTimeline stages (the stage-sum is what a schedule
       with NO overlap pays per iteration);
    2. Prefetcher loop — host-thread double buffering (the pre-pipeline
       overlap story);
    3. serial epoch_scan (pipeline_depth=0) — the in-program baseline;
    4. pipelined epoch_scan (pipeline_depth=1) — the one-step-skew
       schedule, bitwise-identical math.

    Overlap efficiency = serial stage-sum p50 / pipelined per-step p50
    (via StepTimeline.overlap_efficiency); > 1.0 means the pipelined step
    costs less than the sum of its serial stages, i.e. sample/gather
    latency is actually running under compute. Steady-state recompiles of
    the pipelined epoch program are counted and must stay 0."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quiver_tpu import DistributedTrainer, GraphSageSampler, Prefetcher
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.obs.registry import TRAIN_OVERLAP_EFFICIENCY
    from quiver_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, make_mesh
    from quiver_tpu.parallel.train import make_train_step
    from quiver_tpu.utils.trace import Timer

    n = topo.node_count
    timeline = StepTimeline()

    # -- 1. serial stage estimator (eager, Timer-synced stages) ---------------
    sampler_e = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=args.batch,
        seed=args.seed, frontier_caps="auto",
    )
    step = jax.jit(make_train_step(model, tx))
    out0 = sampler_e.sample(rng.integers(0, n, args.batch))
    x0 = feature[out0.n_id]
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, x0, out0.adjs
    )["params"]
    opt_state = tx.init(params)

    def iteration(params, opt_state, key):
        seeds = rng.integers(0, n, args.batch)
        with Timer("sample", quiet=True, registry=timeline):
            out = sampler_e.sample(seeds)
            jax.block_until_ready(out.n_id)
        with Timer("gather", quiet=True, registry=timeline):
            x = feature[out.n_id]
            jax.block_until_ready(x)
        seed_ids = out.n_id[: args.batch]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = seed_ids >= 0
        with Timer("train_step", quiet=True, registry=timeline):
            res = step(params, opt_state, x, out.adjs, labels, mask, key)
            jax.block_until_ready(res[2])
        return res

    t0 = time.time()
    for i in range(max(args.warmup, 1)):
        params, opt_state, loss = iteration(
            params, opt_state, jax.random.PRNGKey(i)
        )
    timeline.clear()  # warmup iterations carry compiles
    for i in range(args.iters):
        params, opt_state, loss = iteration(
            params, opt_state, jax.random.PRNGKey(100 + i)
        )
    jax.block_until_ready(loss)
    serial_ms = {
        name: timeline.stats(name).quantile(0.5) * 1e3
        for name in ("sample", "gather", "train_step")
    }
    serial_sum_ms = sum(serial_ms.values())
    log(f"serial stage estimator: {time.time() - t0:.1f}s "
        f"(stage-sum p50 {serial_sum_ms:.2f} ms/iter)")

    # -- 2. Prefetcher loop (host-thread overlap) -----------------------------
    depth = max(args.prefetch, 1)
    seed_stream = [rng.integers(0, n, args.batch) for _ in range(args.iters)]
    pf = Prefetcher(sampler_e, feature, depth=depth)
    t0 = time.time()
    for i, batch in enumerate(pf.run(seed_stream)):
        seed_ids = batch.out.n_id[: args.batch]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = seed_ids >= 0
        params, opt_state, loss = step(
            params, opt_state, batch.x, batch.out.adjs, labels, mask,
            jax.random.PRNGKey(200 + i),
        )
    jax.block_until_ready(loss)
    prefetch_iter_ms = (time.time() - t0) / args.iters * 1e3

    # -- 3+4. serial vs pipelined epoch_scan ----------------------------------
    mesh = make_mesh()
    workers = mesh.shape[DATA_AXIS] * (
        mesh.shape[FEATURE_AXIS] if args.seed_sharding == "all" else 1
    )
    local_batch = -(-args.batch // workers)
    sampler = GraphSageSampler(
        topo, args.fanout, mode=args.mode, seed_capacity=local_batch,
        seed=args.seed, frontier_caps="auto",
    )
    sampler.sample(rng.integers(0, n, local_batch))
    train_idx = rng.permutation(n)[: args.train_nodes]

    def scan_epochs(pipeline_depth):
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, tx, local_batch=local_batch,
            seed_sharding=args.seed_sharding, pipeline_depth=pipeline_depth,
        )
        params, opt_state = trainer.init(jax.random.PRNGKey(0))
        t0 = time.time()
        seed_mat = trainer.pack_epoch(train_idx, key=0)
        # two warmup epochs: the first compiles against init()'s
        # uncommitted params, the second against the scan's own sharded
        # outputs — the steady-state signature. Counting recompiles from
        # here on, zero is the requirement.
        for _ in range(2):
            params, opt_state, losses = trainer.epoch_scan(
                params, opt_state, seed_mat, labels_all,
                jax.random.PRNGKey(1),
            )
        jax.block_until_ready(losses)
        steps = int(seed_mat.shape[0])
        log(f"depth={pipeline_depth} scan warmup+compile: "
            f"{time.time() - t0:.1f}s ({steps} steps/epoch)")
        cache_size = getattr(trainer._epoch_fn, "_cache_size", None)
        c0 = cache_size() if cache_size else None
        times = []
        for e in range(epochs):
            t0 = time.time()
            seed_mat = trainer.pack_epoch(train_idx, key=e + 1)
            params, opt_state, losses = trainer.epoch_scan(
                params, opt_state, seed_mat, labels_all,
                jax.random.PRNGKey(2 + e),
            )
            final_loss = float(losses[-1])  # readback inside the clock
            times.append(time.time() - t0)
            if pipeline_depth:
                timeline.observe("pipelined_step", times[-1] / steps)
        recompiles = (cache_size() - c0) if cache_size else None
        return trainer, trimmed_mean(times), steps, final_loss, recompiles

    _, serial_epoch_s, steps, _, _ = scan_epochs(0)
    trainer, pipe_epoch_s, steps, final_loss, recompiles = scan_epochs(1)
    pipe_iter_ms = pipe_epoch_s / steps * 1e3
    eff = timeline.overlap_efficiency(
        ("sample", "gather", "train_step"), "pipelined_step"
    )
    if eff is not None:
        trainer.metrics.set(TRAIN_OVERLAP_EFFICIENCY, np.float32(eff))
    scan_speedup = round(serial_epoch_s / pipe_epoch_s, 3)
    log("stage timeline (serial estimator + pipelined steps):\n"
        + timeline.report())

    emit(
        "pipeline-stage-sum", serial_sum_ms, "ms/iter", None,
        mode="SERIAL-STAGES",
        sample_ms=round(serial_ms["sample"], 2),
        gather_ms=round(serial_ms["gather"], 2),
        train_ms=round(serial_ms["train_step"], 2),
        batch=args.batch,
    )
    emit(
        "pipeline-prefetch-iter", prefetch_iter_ms, "ms/iter", None,
        mode="PREFETCH", prefetch=depth, batch=args.batch,
    )
    emit(
        "pipeline-serial-scan-iter", serial_epoch_s / steps * 1e3,
        "ms/iter", None, mode="FUSED-SCAN", iters_per_epoch=steps,
        epoch_s=round(serial_epoch_s, 3), batch=args.batch,
    )
    emit(
        "e2e-epoch-time",
        pipe_epoch_s,
        "s",
        BASELINE_EPOCH_S,
        invert=True,
        iter_ms=round(pipe_iter_ms, 2),
        iters_per_epoch=steps,
        batch=args.batch,
        model=args.model,
        mode="FUSED-PIPELINED",
        topo_mode=args.mode,
        seed_sharding=args.seed_sharding,
        bf16=bool(args.bf16),
        cache_ratio=args.cache_ratio,
        pipeline_depth=1,
        overlap_efficiency=(None if eff is None else round(eff, 3)),
        scan_speedup=scan_speedup,
        recompiles_steady=recompiles,
        measured="direct",
        loss=round(final_loss, 4),
    )
    log(trainer.metrics_report())
    write_metrics(trainer, lane="epoch-pipelined")


def _emit_epoch(args, iter_s, loss, fused: bool):
    iters_per_epoch = -(-args.train_nodes // args.batch)
    epoch_s = iter_s * iters_per_epoch

    emit(
        "e2e-epoch-time",
        epoch_s,
        "s",
        BASELINE_EPOCH_S,
        invert=True,
        iter_ms=round(iter_s * 1e3, 2),
        iters_per_epoch=iters_per_epoch,
        batch=args.batch,
        model=args.model,
        mode="FUSED" if fused else args.mode,
        prefetch=0 if fused else args.prefetch,  # fused never prefetches
        precision="bf16" if args.bf16 else "f32",
        final_loss=round(float(loss), 4),
    )


if __name__ == "__main__":
    main()
