"""Sampler configuration sweep in ONE process: dedup strategies x batch
sizes, all fused-stream dispatch.

Chip time on the tunnel is dominated by backend init (~min) and per-config
compiles (~min each, amortized by the persistent cache); running the sweep
in one process pays init once. Emits one JSON line per configuration
(same schema as bench_sampler) — feed the winner back into bench.py's
headline CHILD config.

    python -m benchmarks.sweep_sampler                       # default grid
    python -m benchmarks.sweep_sampler --batches 2048 8192 --dedups map
"""

import time

import numpy as np

from benchmarks.common import base_parser, build_graph, emit, log, run_guarded

BASELINE_UVA_SEPS = 34.29e6


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--batches", type=int, nargs="+",
                   default=[2048, 4096, 8192])
    p.add_argument("--dedups", nargs="+", default=["sort", "map"],
                   choices=["sort", "map"])
    p.add_argument("--stream", type=int, default=64)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _stream_once(sampler, topo, batch, stream, rng, reps):
    import jax
    import jax.numpy as jnp
    from jax import lax

    run, caps = sampler._compiled(batch)
    ins = (batch,) + tuple(caps[:-1])
    max_epb = sum(i * k for i, k in zip(ins, sampler.sizes))
    stream = max(1, min(stream, (2**31 - 1) // max(max_epb, 1)))
    n_vec = jnp.full((stream,), jnp.int32(batch))

    @jax.jit
    def streamf(topo_dev, seed_mat, nums, key0):
        def step(carry, xs):
            key, total, oflo = carry
            seeds, n = xs
            key, sub = jax.random.split(key)
            _, _, _, overflow, ec, _ = run(topo_dev, seeds, n, sub)
            return (key, total + jnp.sum(jnp.stack(ec)), oflo + overflow), None
        init = (key0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        (_, total, oflo), _ = lax.scan(step, init, (seed_mat, nums))
        return total, oflo

    def one_rep():
        seed_np = rng.integers(0, topo.node_count, (stream, batch)).astype(np.int32)
        key = jax.random.PRNGKey(int(rng.integers(1 << 30)))
        t0 = time.time()
        total, oflo = streamf(sampler.topo, jnp.asarray(seed_np), n_vec, key)
        total, oflo = int(total), int(oflo)
        return total / (time.time() - t0), oflo

    t0 = time.time()
    one_rep()  # compile
    log(f"  compile {time.time()-t0:.1f}s (stream={stream})")
    results = [one_rep() for _ in range(reps)]
    return float(np.median([r[0] for r in results])), results[-1][1], stream


def _body(args):
    from quiver_tpu import GraphSageSampler

    topo = build_graph(args)
    rng = np.random.default_rng(args.seed)

    for dedup in args.dedups:
        for batch in args.batches:
            log(f"config dedup={dedup} batch={batch}")
            sampler = GraphSageSampler(
                topo, args.fanout, mode="HBM", seed_capacity=batch,
                seed=args.seed, dedup=dedup, frontier_caps="auto",
            )
            # plan auto caps from one eager batch
            sampler.sample(rng.integers(0, topo.node_count, batch))
            try:
                seps, oflo, stream = _stream_once(
                    sampler, topo, batch, args.stream, rng, args.reps
                )
            except Exception as e:  # noqa: BLE001 — one config must not kill the sweep
                log(f"  config failed: {type(e).__name__}: {str(e)[:200]}")
                continue
            emit(
                "sampled-edges/sec/chip",
                seps,
                "SEPS",
                BASELINE_UVA_SEPS,
                mode="HBM",
                kernel="xla",
                fanout=args.fanout,
                batch=batch,
                caps="auto",
                dedup=dedup,
                dispatch="stream",
                stream_batches=stream,
                overflow=oflo,
            )


if __name__ == "__main__":
    main()
