"""Sampler configuration sweep in ONE process: dedup strategies x batch
sizes, all fused-stream dispatch.

Chip time on the tunnel is dominated by backend init (~min) and per-config
compiles (~min each, amortized by the persistent cache); running the sweep
in one process pays init once, and every sampler shares ONE device-resident
topology (GraphSageSampler(device_topo=...)) so the ~500MB CSR crosses the
link once, not once per configuration. Emits one JSON line per config
(same schema as bench_sampler) — feed the winner back into bench.py's
headline CHILD config.

    python -m benchmarks.sweep_sampler                       # default grid
    python -m benchmarks.sweep_sampler --batches 2048 8192 --dedups map
"""

import numpy as np

from benchmarks.common import (
    BASELINE_UVA_SEPS,
    base_parser,
    build_graph,
    emit,
    log,
    run_guarded,
    stream_seps,
)


def main():
    p = base_parser(__doc__)
    p.add_argument("--fanout", type=int, nargs="+", default=[15, 10, 5])
    p.add_argument("--batches", type=int, nargs="+",
                   default=[2048, 4096, 8192])
    p.add_argument("--dedups", nargs="+", default=["sort", "map", "scan"],
                   choices=["sort", "map", "scan"])
    p.add_argument("--stream", type=int, default=64)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    from quiver_tpu import GraphSageSampler
    from quiver_tpu.core.config import SampleMode

    topo = build_graph(args)
    rng = np.random.default_rng(args.seed)
    dev_topo = topo.to_device(SampleMode.HBM)  # shared across every config

    # evidence-ordered: the strategy head-to-head at the headline batch
    # first (a short chip window must decide dedup before batch scaling)
    grid = sorted(
        ((d, b) for d in args.dedups for b in args.batches),
        key=lambda db: (db[1] != args.batches[0], args.batches.index(db[1]),
                        args.dedups.index(db[0])),
    )
    for dedup, batch in grid:
            log(f"config dedup={dedup} batch={batch}")
            sampler = GraphSageSampler(
                topo, args.fanout, mode="HBM", seed_capacity=batch,
                seed=args.seed, dedup=dedup, frontier_caps="auto",
                device_topo=dev_topo,
            )
            # plan auto caps from one eager batch
            sampler.sample(rng.integers(0, topo.node_count, batch))
            try:
                res = stream_seps(
                    sampler, topo.node_count, batch, args.stream, rng,
                    args.reps,
                )
            except Exception as e:  # noqa: BLE001 — one config must not kill the sweep
                log(f"  config failed: {type(e).__name__}: {str(e)[:200]}")
                continue
            if res is None:
                continue
            seps, oflo, stream = res
            emit(
                "sampled-edges/sec/chip",
                seps,
                "SEPS",
                BASELINE_UVA_SEPS,
                mode="HBM",
                kernel="xla",
                fanout=args.fanout,
                batch=batch,
                caps="auto",
                dedup=dedup,
                dispatch="stream",
                stream_batches=stream,
                overflow=oflo,
            )


if __name__ == "__main__":
    main()
