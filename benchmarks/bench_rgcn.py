"""Heterogeneous R-GCN end-to-end epoch benchmark.

No reference baseline exists (torch-quiver's hetero/SAINT support is rotted
stubs, SURVEY §2.5) — this tracks the framework's own hetero capability:
MAG-style schema (paper-cites-paper, author-writes-paper,
inst-employs-author), per-relation sampling with auto frontier caps
(VERDICT r1 item 7: worst-case caps overshoot ~3x on power-law graphs and
R-GCN pays it in every gather/aggregate), relational message passing.
Methodology matches bench_epoch: trimmed-mean iteration time x
iterations-per-epoch.
"""

import time

import numpy as np

from benchmarks.common import (
    base_parser,
    emit,
    init_backend,
    log,
    run_guarded,
    trimmed_mean,
)


def main():
    p = base_parser(__doc__)
    p.add_argument("--feature-dim", type=int, default=128)
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--fanout", type=int, nargs="+", default=[8, 4])
    p.add_argument("--caps", default="auto", choices=["auto", "worst"])
    p.add_argument(
        "--stream", type=int, default=0, metavar="N",
        help="also measure N training steps as ONE compiled program "
        "(lax.scan: hetero sample -> tiered gather -> R-GCN fwd/bwd -> "
        "update, params in carry, one loss readback) — the fused-epoch "
        "dispatch that sidesteps per-call host round-trips",
    )
    p.set_defaults(nodes=200_000, batch=512, iters=30, warmup=3)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    init_backend(
        retries=getattr(args, "backend_retries", 1),
        delay=getattr(args, "backend_retry_delay", 15.0),
    )
    from benchmarks.common import _DEGRADED_REASON, apply_smoke

    if _DEGRADED_REASON is not None:
        args.smoke = True
    apply_smoke(args)

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import HeteroCSRTopo, HeteroFeature, HeteroGraphSampler
    from quiver_tpu.models.rgcn import RGCN
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    n_paper = args.nodes
    n_author = n_paper // 2
    n_inst = max(n_paper // 40, 4)
    rng = np.random.default_rng(args.seed)
    t0 = time.time()
    topo = HeteroCSRTopo(
        {"paper": n_paper, "author": n_author, "inst": n_inst},
        {
            ("paper", "cites", "paper"): generate_pareto_graph(
                n_paper, 10.0, seed=args.seed
            ),
            ("author", "writes", "paper"): np.stack([
                rng.integers(0, n_author, n_paper * 3),
                rng.integers(0, n_paper, n_paper * 3),
            ]),
            ("inst", "employs", "author"): np.stack([
                rng.integers(0, n_inst, n_author * 2),
                rng.integers(0, n_author, n_author * 2),
            ]),
        },
    )
    log(f"hetero graph: {n_paper}+{n_author}+{n_inst} nodes "
        f"({time.time() - t0:.1f}s build)")

    feats = {
        t: rng.normal(size=(c, args.feature_dim)).astype(np.float32)
        for t, c in
        {"paper": n_paper, "author": n_author, "inst": n_inst}.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="4G")
    del feats
    labels_all = jnp.asarray(
        rng.integers(0, args.classes, n_paper).astype(np.int32)
    )

    sampler = HeteroGraphSampler(
        topo, args.fanout, input_type="paper", seed_capacity=args.batch,
        frontier_caps="auto" if args.caps == "auto" else None, seed=args.seed,
    )
    model = RGCN(hidden=args.hidden, num_classes=args.classes,
                 target_type="paper", num_layers=len(args.fanout))
    tx = optax.adam(5e-3)

    out = sampler.sample(rng.integers(0, n_paper, args.batch))
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, feature[out.n_id], out.adjs
    )["params"]
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x_dict, layers, labels, mask, key):
        def loss_fn(p):
            logp = model.apply({"params": p}, x_dict, layers, train=True,
                               rngs={"dropout": key})
            ll = jnp.take_along_axis(
                logp, jnp.clip(labels, 0)[:, None], axis=1
            )[:, 0]
            w = mask.astype(logp.dtype)
            return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def iteration(params, opt_state, i):
        seeds = rng.integers(0, n_paper, args.batch)
        out = sampler.sample(seeds)
        seed_ids = out.n_id["paper"][: args.batch]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = seed_ids >= 0
        return step(params, opt_state, feature[out.n_id], out.adjs, labels,
                    mask, jax.random.PRNGKey(i))

    t0 = time.time()
    for i in range(args.warmup):
        params, opt_state, loss = iteration(params, opt_state, i)
    jax.block_until_ready(loss)
    log(f"warmup+compile: {time.time() - t0:.1f}s")

    times = []
    for i in range(args.iters):
        t0 = time.time()
        params, opt_state, loss = iteration(params, opt_state, 100 + i)
        jax.block_until_ready(loss)
        times.append(time.time() - t0)

    iter_s = trimmed_mean(times)
    train_nodes = n_paper // 10
    iters_per_epoch = -(-train_nodes // args.batch)

    emit(
        "rgcn-epoch-time",
        iter_s * iters_per_epoch,
        "s",
        None,
        iter_ms=round(iter_s * 1e3, 2),
        iters_per_epoch=iters_per_epoch,
        caps=args.caps,
        batch=args.batch,
        fanout=args.fanout,
        dispatch="percall",
        final_loss=round(float(loss), 4),
    )

    # AFTER the per-call record is safely flushed: a stream-side hang or
    # timeout must not cost the measurement already in hand
    if args.stream:
        try:
            _stream_epoch(args, sampler, feature, labels_all, step, params,
                          opt_state, rng, n_paper, iters_per_epoch)
        except Exception as e:  # noqa: BLE001 — per-call record stands
            log(f"stream measure failed (per-call record stands): "
                f"{type(e).__name__}: {str(e)[:200]}")


def _stream_epoch(args, sampler, feature, labels_all, step, params,
                  opt_state, rng, n_paper, iters_per_epoch, reps: int = 3):
    """N hetero training steps as ONE compiled program (lax.scan)."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax

    run = sampler._compiled(args.batch)

    @jax.jit
    def scan_train(params, opt_state, dev_topos, seed_mat, key0):
        keys = jax.random.split(key0, seed_mat.shape[0])

        def body(carry, xs):
            p, o, oflo = carry
            seeds, k = xs
            ks, kd = jax.random.split(k)
            frontier, counts, layers, overflow, _ = run(
                dev_topos, seeds, jnp.int32(args.batch), ks
            )
            seed_ids = frontier["paper"][: args.batch]
            labels = labels_all[jnp.clip(seed_ids, 0)]
            mask = seed_ids >= 0
            p, o, loss = step(p, o, feature[frontier], layers, labels,
                              mask, kd)
            return (p, o, oflo + overflow), loss

        (p, o, oflo), losses = lax.scan(
            body, (params, opt_state, jnp.zeros((), jnp.int32)),
            (seed_mat, keys),
        )
        return p, o, losses, oflo

    def one_rep():
        seed_mat = jnp.asarray(
            rng.integers(0, n_paper, (args.stream, args.batch)).astype(
                np.int32
            )
        )
        key = jax.random.PRNGKey(int(rng.integers(1 << 30)))
        t0 = _time.time()
        p, o, losses, oflo = scan_train(params, opt_state,
                                        sampler.dev_topos, seed_mat, key)
        final = float(losses[-1])
        return (_time.time() - t0) / args.stream, final, int(oflo)

    t0 = _time.time()
    one_rep()  # compile
    log(f"stream compile: {_time.time()-t0:.1f}s "
        f"({args.stream} steps/scan)")
    results = [one_rep() for _ in range(reps)]
    iter_s = float(np.median([r[0] for r in results]))
    emit(
        "rgcn-epoch-time",
        iter_s * iters_per_epoch,
        "s",
        None,
        iter_ms=round(iter_s * 1e3, 2),
        iters_per_epoch=iters_per_epoch,
        caps=args.caps,
        batch=args.batch,
        fanout=args.fanout,
        dispatch="stream",
        stream_batches=args.stream,
        overflow=results[-1][2],
        final_loss=round(results[-1][1], 4),
    )



if __name__ == "__main__":
    main()
