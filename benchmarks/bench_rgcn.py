"""Heterogeneous R-GCN end-to-end epoch benchmark.

No reference baseline exists (torch-quiver's hetero/SAINT support is rotted
stubs, SURVEY §2.5) — this tracks the framework's own hetero capability:
MAG-style schema (paper-cites-paper, author-writes-paper,
inst-employs-author), per-relation sampling with auto frontier caps
(VERDICT r1 item 7: worst-case caps overshoot ~3x on power-law graphs and
R-GCN pays it in every gather/aggregate), relational message passing.
Methodology matches bench_epoch: trimmed-mean iteration time x
iterations-per-epoch.
"""

import time

import numpy as np

from benchmarks.common import (
    base_parser,
    emit,
    init_backend,
    log,
    run_guarded,
    trimmed_mean,
)


def main():
    p = base_parser(__doc__)
    p.add_argument("--feature-dim", type=int, default=128)
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--fanout", type=int, nargs="+", default=[8, 4])
    p.add_argument("--caps", default="auto", choices=["auto", "worst"])
    p.add_argument(
        "--topo-sharding",
        default="replicated",
        choices=["replicated", "mesh"],
        dest="topo_sharding",
        help="relation placement: 'replicated' (every chip holds every "
        "relation's full CSR) or 'mesh' — each relation partitioned "
        "across the mesh's feature axis (~1/F topology bytes per chip), "
        "sampled by DistHeteroSampler through ONE shared BucketRoute "
        "plan per (hop, destination type); the record carries the exact "
        "per-edge-type lanes-per-hop comm model + the measured "
        "per-(hop, edge type) fallback overflow",
    )
    p.add_argument(
        "--routed-alpha",
        type=float,
        default=2.0,
        metavar="A",
        dest="routed_alpha",
        help="--topo-sharding mesh: capped-bucket factor — per-destination "
        "bucket capacity ceil(A*S_t/F) per (hop, dst type); 0 = uncapped "
        "full-length buckets. Overflow lanes are fallback-served (exact) "
        "and counted per (hop, edge type)",
    )
    p.add_argument(
        "--weighted",
        action="store_true",
        help="attach per-edge weights to every relation and draw "
        "inverse-CDF weighted samples (mesh lane: the owner searches its "
        "routed prefix-weight segment; +F*cap f32 lanes per relation "
        "per hop in the comm model)",
    )
    p.add_argument(
        "--stream", type=int, default=0, metavar="N",
        help="also measure N training steps as ONE compiled program "
        "(lax.scan: hetero sample -> tiered gather -> R-GCN fwd/bwd -> "
        "update, params in carry, one loss readback) — the fused-epoch "
        "dispatch that sidesteps per-call host round-trips",
    )
    p.set_defaults(nodes=200_000, batch=512, iters=30, warmup=3)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


def _body(args):
    init_backend(
        retries=getattr(args, "backend_retries", 1),
        delay=getattr(args, "backend_retry_delay", 15.0),
    )
    from benchmarks.common import _DEGRADED_REASON, apply_smoke

    if _DEGRADED_REASON is not None:
        args.smoke = True
    apply_smoke(args)

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import HeteroCSRTopo, HeteroFeature, HeteroGraphSampler
    from quiver_tpu.models.rgcn import RGCN
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    n_paper = args.nodes
    n_author = n_paper // 2
    n_inst = max(n_paper // 40, 4)
    rng = np.random.default_rng(args.seed)
    t0 = time.time()
    topo = HeteroCSRTopo(
        {"paper": n_paper, "author": n_author, "inst": n_inst},
        {
            ("paper", "cites", "paper"): generate_pareto_graph(
                n_paper, 10.0, seed=args.seed
            ),
            ("author", "writes", "paper"): np.stack([
                rng.integers(0, n_author, n_paper * 3),
                rng.integers(0, n_paper, n_paper * 3),
            ]),
            ("inst", "employs", "author"): np.stack([
                rng.integers(0, n_inst, n_author * 2),
                rng.integers(0, n_author, n_author * 2),
            ]),
        },
    )
    log(f"hetero graph: {n_paper}+{n_author}+{n_inst} nodes "
        f"({time.time() - t0:.1f}s build)")
    if args.weighted:
        wrng = np.random.default_rng(args.seed + 5)
        for et in topo.relations:
            topo.set_edge_weight(
                et, np.exp(wrng.normal(size=topo.relations[et].edge_count))
            )

    feats = {
        t: rng.normal(size=(c, args.feature_dim)).astype(np.float32)
        for t, c in
        {"paper": n_paper, "author": n_author, "inst": n_inst}.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="4G")
    del feats
    labels_all = jnp.asarray(
        rng.integers(0, args.classes, n_paper).astype(np.int32)
    )

    model = RGCN(hidden=args.hidden, num_classes=args.classes,
                 target_type="paper", num_layers=len(args.fanout))
    tx = optax.adam(5e-3)

    if args.topo_sharding == "mesh":
        return _body_mesh(args, topo, feature, labels_all, model, tx, rng,
                          n_paper)

    sampler = HeteroGraphSampler(
        topo, args.fanout, input_type="paper", seed_capacity=args.batch,
        frontier_caps="auto" if args.caps == "auto" else None,
        weighted=args.weighted, seed=args.seed,
    )

    out = sampler.sample(rng.integers(0, n_paper, args.batch))
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, feature[out.n_id], out.adjs
    )["params"]
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x_dict, layers, labels, mask, key):
        def loss_fn(p):
            logp = model.apply({"params": p}, x_dict, layers, train=True,
                               rngs={"dropout": key})
            ll = jnp.take_along_axis(
                logp, jnp.clip(labels, 0)[:, None], axis=1
            )[:, 0]
            w = mask.astype(logp.dtype)
            return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def iteration(params, opt_state, i):
        seeds = rng.integers(0, n_paper, args.batch)
        out = sampler.sample(seeds)
        seed_ids = out.n_id["paper"][: args.batch]
        labels = labels_all[jnp.clip(seed_ids, 0)]
        mask = seed_ids >= 0
        return step(params, opt_state, feature[out.n_id], out.adjs, labels,
                    mask, jax.random.PRNGKey(i))

    t0 = time.time()
    for i in range(args.warmup):
        params, opt_state, loss = iteration(params, opt_state, i)
    jax.block_until_ready(loss)
    log(f"warmup+compile: {time.time() - t0:.1f}s")

    times = []
    for i in range(args.iters):
        t0 = time.time()
        params, opt_state, loss = iteration(params, opt_state, 100 + i)
        jax.block_until_ready(loss)
        times.append(time.time() - t0)

    iter_s = trimmed_mean(times)
    train_nodes = n_paper // 10
    iters_per_epoch = -(-train_nodes // args.batch)

    emit(
        "rgcn-epoch-time",
        iter_s * iters_per_epoch,
        "s",
        None,
        iter_ms=round(iter_s * 1e3, 2),
        iters_per_epoch=iters_per_epoch,
        caps=args.caps,
        batch=args.batch,
        fanout=args.fanout,
        dispatch="percall",
        topo_sharding="replicated",
        weighted=args.weighted,
        final_loss=round(float(loss), 4),
    )

    # AFTER the per-call record is safely flushed: a stream-side hang or
    # timeout must not cost the measurement already in hand
    if args.stream:
        try:
            _stream_epoch(args, sampler, feature, labels_all, step, params,
                          opt_state, rng, n_paper, iters_per_epoch)
        except Exception as e:  # noqa: BLE001 — per-call record stands
            log(f"stream measure failed (per-call record stands): "
                f"{type(e).__name__}: {str(e)[:200]}")


def _stream_epoch(args, sampler, feature, labels_all, step, params,
                  opt_state, rng, n_paper, iters_per_epoch, reps: int = 3):
    """N hetero training steps as ONE compiled program (lax.scan)."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax

    run = sampler._compiled(args.batch)

    @jax.jit
    def scan_train(params, opt_state, dev_topos, seed_mat, key0):
        keys = jax.random.split(key0, seed_mat.shape[0])

        def body(carry, xs):
            p, o, oflo = carry
            seeds, k = xs
            ks, kd = jax.random.split(k)
            frontier, counts, layers, overflow, _ = run(
                dev_topos, seeds, jnp.int32(args.batch), ks
            )
            seed_ids = frontier["paper"][: args.batch]
            labels = labels_all[jnp.clip(seed_ids, 0)]
            mask = seed_ids >= 0
            p, o, loss = step(p, o, feature[frontier], layers, labels,
                              mask, kd)
            return (p, o, oflo + overflow), loss

        (p, o, oflo), losses = lax.scan(
            body, (params, opt_state, jnp.zeros((), jnp.int32)),
            (seed_mat, keys),
        )
        return p, o, losses, oflo

    def one_rep():
        seed_mat = jnp.asarray(
            rng.integers(0, n_paper, (args.stream, args.batch)).astype(
                np.int32
            )
        )
        key = jax.random.PRNGKey(int(rng.integers(1 << 30)))
        t0 = _time.time()
        p, o, losses, oflo = scan_train(params, opt_state,
                                        sampler.dev_topos, seed_mat, key)
        final = float(losses[-1])
        return (_time.time() - t0) / args.stream, final, int(oflo)

    t0 = _time.time()
    one_rep()  # compile
    log(f"stream compile: {_time.time()-t0:.1f}s "
        f"({args.stream} steps/scan)")
    results = [one_rep() for _ in range(reps)]
    iter_s = float(np.median([r[0] for r in results]))
    emit(
        "rgcn-epoch-time",
        iter_s * iters_per_epoch,
        "s",
        None,
        iter_ms=round(iter_s * 1e3, 2),
        iters_per_epoch=iters_per_epoch,
        caps=args.caps,
        batch=args.batch,
        fanout=args.fanout,
        dispatch="stream",
        stream_batches=args.stream,
        overflow=results[-1][2],
        final_loss=round(results[-1][1], 4),
    )

def _hetero_comm_model(sampler, seed_cap: int) -> dict:
    """Exact per-device lanes-per-hop model of the mesh-sharded hetero
    sampler.

    The shared route plan moves each (hop, destination type) frontier's
    ids ONCE — ``F * cap_t`` lanes, ``cap_t = ceil(alpha * S_t / F)`` —
    and every relation into that type reuses the cached routed ids. Each
    uniform relation then adds ``F * cap_t`` (degrees back) +
    ``2 * F * cap_t * k`` (offsets out, neighbor blocks back); a weighted
    relation adds one more ``F * cap_t`` f32 exchange (row weight totals
    back). Bucket shapes are static, so the model is exact; the measured
    per-(hop, edge type) fallback overflow rides alongside it.
    """
    from quiver_tpu.sampling.dist import routed_sample_cap

    F = sampler.workers
    alpha = sampler.routed_alpha
    lanes, lanes_unc, hop_caps = [], [], []
    for active, caps_prev, _ in sampler._plan(seed_cap,
                                              sampler._cap_overrides):
        hop, hop_unc, caps_t = 0, 0, {}
        for t in sorted({et[2] for et in active}):
            S_t = caps_prev[t]
            cap_t = routed_sample_cap(S_t, F, alpha) or S_t
            caps_t[t] = cap_t
            hop += F * cap_t  # shared plan: ids out once per dst type
            hop_unc += F * S_t
        for et, k in sorted(active.items(), key=lambda kv: str(kv[0])):
            cap_t, S_t = caps_t[et[2]], caps_prev[et[2]]
            extra = 1 if et in sampler.weighted_rels else 0
            hop += F * cap_t * (1 + extra + 2 * k)
            hop_unc += F * S_t * (1 + extra + 2 * k)
        hop_caps.append(caps_t)
        lanes.append(hop)
        lanes_unc.append(hop_unc)
    plan = sampler.dev_topos.plan
    return {
        "topo_sharding": "mesh",
        "routed_alpha": alpha,
        "hop_caps": hop_caps,
        "lanes_per_hop": lanes,
        "lanes_per_hop_uncapped": lanes_unc,
        "comm_reduction": round(sum(lanes_unc) / max(sum(lanes), 1), 2),
        "topo_bytes_per_chip": plan["per_chip_bytes"],
        "topo_bytes_replicated": plan["replicated_bytes"],
        "topo_shrink": round(plan["shrink_factor"], 2),
    }


def _body_mesh(args, topo, feature, labels_all, model, tx, rng, n_paper):
    """--topo-sharding mesh lane: DistHeteroSampler over per-relation
    mesh partitions. Methodology matches the replicated lane (trimmed-mean
    iteration time x iterations-per-epoch); each iteration samples every
    worker's block, runs the R-GCN fwd/bwd per block, and applies the
    worker-averaged update — the record adds the exact per-edge-type
    lanes-per-hop comm model and the measured per-(hop, edge type)
    fallback overflow."""
    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import DistHeteroSampler
    from quiver_tpu.parallel.mesh import make_mesh

    if args.stream:
        log("WARNING: --stream is not supported with --topo-sharding mesh; "
            "measuring the per-call dispatch loop only")
    F = len(jax.devices())
    mesh = make_mesh(data=1, feature=F)
    sampler = DistHeteroSampler(
        topo, args.fanout, input_type="paper", mesh=mesh,
        seed_capacity=-(-args.batch // F),
        frontier_caps="auto" if args.caps == "auto" else None,
        weighted=args.weighted, routed_alpha=args.routed_alpha or None,
        seed=args.seed,
    )
    W = sampler.workers
    cap = -(-args.batch // F)

    def sample_blocks(i):
        seeds = rng.integers(0, n_paper, args.batch)
        outs = sampler.sample_per_worker(seeds, key=jax.random.PRNGKey(i))
        return outs, np.array_split(seeds, W)

    outs, _ = sample_blocks(0)
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, feature[outs[0].n_id],
        outs[0].adjs,
    )["params"]
    opt_state = tx.init(params)

    @jax.jit
    def grad_step(params, x_dict, layers, labels, mask, key):
        def loss_fn(p):
            logp = model.apply({"params": p}, x_dict, layers, train=True,
                               rngs={"dropout": key})
            ll = jnp.take_along_axis(
                logp, jnp.clip(labels, 0)[:, None], axis=1
            )[:, 0]
            w = mask.astype(logp.dtype)
            return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)

        return jax.value_and_grad(loss_fn)(params)

    @jax.jit
    def apply_update(params, opt_state, grads):
        updates, opt_state = tx.update(
            jax.tree_util.tree_map(lambda g: g / W, grads), opt_state,
            params
        )
        return optax.apply_updates(params, updates), opt_state

    def iteration(params, opt_state, i):
        outs, _ = sample_blocks(i)
        grads_acc, loss = None, None
        for o in outs:
            seed_ids = o.n_id["paper"][:cap]
            labels = labels_all[jnp.clip(seed_ids, 0)]
            loss, grads = grad_step(params, feature[o.n_id], o.adjs,
                                    labels, seed_ids >= 0,
                                    jax.random.PRNGKey(i))
            grads_acc = grads if grads_acc is None else \
                jax.tree_util.tree_map(jnp.add, grads_acc, grads)
        params, opt_state = apply_update(params, opt_state, grads_acc)
        return params, opt_state, loss

    t0 = time.time()
    for i in range(args.warmup):
        params, opt_state, loss = iteration(params, opt_state, i)
    jax.block_until_ready(loss)
    log(f"warmup+compile: {time.time() - t0:.1f}s ({W} workers)")

    times = []
    for i in range(args.iters):
        t0 = time.time()
        params, opt_state, loss = iteration(params, opt_state, 100 + i)
        jax.block_until_ready(loss)
        times.append(time.time() - t0)

    iter_s = trimmed_mean(times)
    train_nodes = n_paper // 10
    iters_per_epoch = -(-train_nodes // args.batch)
    model_rec = _hetero_comm_model(sampler, cap)
    ov = sampler.last_sample_overflow_by_rel or {}
    emit(
        "rgcn-epoch-time",
        iter_s * iters_per_epoch,
        "s",
        None,
        iter_ms=round(iter_s * 1e3, 2),
        iters_per_epoch=iters_per_epoch,
        caps=args.caps,
        batch=args.batch,
        fanout=args.fanout,
        dispatch="percall",
        mesh_devices=W,
        weighted=args.weighted,
        sample_overflow={
            f"hop{li}:{'-'.join(et)}": int(v) for (li, et), v in ov.items()
        },
        final_loss=round(float(loss), 4),
        **model_rec,
    )


if __name__ == "__main__":
    main()
