"""Primitive micro-benchmarks: the building blocks of the sampler hot path.

Measures, via fused scans (one compiled program per primitive, distinct
inputs per step, full-output checksums (a sliced element would let XLA dead-code the op) and one scalar readback — the only honest methodology over a
~90 ms-RTT tunnel), the per-element cost of exactly the operations the
three dedup strategies are built from:

* ``sort``        — jnp.sort of int32 (the scan/sort strategies' engine)
* ``argsort-pair``— stable argsort + payload gather (what masked_unique does)
* ``gather``      — random int32 gather (every strategy)
* ``scatter-set`` — .at[].set into a same-sized buffer (sort-path compaction)
* ``scatter-min`` — .at[].min into a node_count-sized map (map strategy)
* ``cummax``      — lax.cummax (scan strategy's run-representative)

The r3 link data showed TPU sort at ~1.8 ms/M while reindex ran tens of ms
— these rows decide whether XLA scatters are the serialization point and
therefore which dedup strategy should win (ops/reindex.py). ~2 minutes of
chip time; scheduled early in the scoreboard so even a brief window lands
the diagnosis.

Reference counterpart: none (the reference's primitives are thrust/cub
calls benchmarked nowhere; this is chip triage tooling).
"""

import time

import numpy as np

from benchmarks.common import base_parser, emit, log, run_guarded


def _measure(name, make_inputs, op, n_elems: int, reps: int, key):
    """Median Melem/s of ``op`` over a fused scan of ``reps`` distinct
    inputs. ``make_inputs(key, reps)`` returns the stacked xs."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    xs = make_inputs(key, reps)

    @jax.jit
    def run(xs_all):
        def step(carry, xs_one):
            return carry + op(xs_one), None
        total, _ = lax.scan(step, jnp.float32(0), xs_all)
        return total

    t0 = time.time()
    jax.block_until_ready(run(xs))
    log(f"{name}: compile {time.time() - t0:.1f}s")
    times = []
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(run(xs))
        times.append(time.time() - t0)
    dt = sorted(times)[1]
    melems = reps * n_elems / dt / 1e6
    emit("primitive-Melem/s", melems, "Melem/s", None, op=name,
         elems=n_elems, reps=reps, ms_per_call=round(dt / reps * 1e3, 3))


def _body(args):
    import jax
    import jax.numpy as jnp

    from benchmarks.common import init_backend, set_record_context

    init_backend(retries=getattr(args, "backend_retries", 1))
    n = 200_000 if args.smoke else 1_000_000
    bound = 500_000 if args.smoke else 2_450_000  # the dense-map size
    reps = 4 if args.smoke else 8
    set_record_context(nodes=bound, smoke=True if args.smoke else None)
    key = jax.random.PRNGKey(args.seed)

    def rand_ids(key, reps, hi=n):
        return jax.random.randint(key, (reps, n), 0, hi, dtype=jnp.int32)

    _measure("sort", rand_ids, lambda x: jnp.sum(jnp.sort(x).astype(jnp.float32)),
             n, reps, key)
    _measure(
        "argsort-pair", rand_ids,
        lambda x: jnp.sum(x[jnp.argsort(x, stable=True)].astype(jnp.float32)),
        n, reps, key)
    table = jnp.arange(bound, dtype=jnp.float32)
    _measure("gather", lambda k, r: rand_ids(k, r, bound),
             lambda i: jnp.sum(table[i]), n, reps, key)
    vals = jnp.arange(n, dtype=jnp.int32)
    _measure(
        "scatter-set", rand_ids,
        lambda i: jnp.sum(jnp.zeros(n, jnp.int32).at[i].set(
            vals, mode="drop").astype(jnp.float32)),
        n, reps, key)
    _measure(
        "scatter-min", lambda k, r: rand_ids(k, r, bound),
        lambda i: jnp.sum(jnp.full(bound, n, jnp.int32).at[i].min(
            vals, mode="drop").astype(jnp.float32)),
        n, reps, key)
    _measure("cummax", rand_ids,
             lambda x: jnp.sum(jax.lax.cummax(x).astype(jnp.float32)),
             n, reps, key)


def main():
    p = base_parser(__doc__)
    args = p.parse_args()
    run_guarded(lambda: _body(args), args)


if __name__ == "__main__":
    main()
